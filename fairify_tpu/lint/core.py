"""Rule-engine core for ``fairify_tpu.lint``: contexts, baseline, runner, CLI.

The lint framework is a cheap whole-repo AST analysis (no imports of the
code under analysis, no jax) that guards the invariants the runtime
subsystems cannot enforce from the inside: every device kernel registered
through ``obs_jit``, no sync fetch stalling the launch queue, no fault
swallowed without a recorded reason, trace-pure jitted bodies, stable jit
signatures, locked shared state, and live chaos coverage.  This module is
the engine; the rules live in ``rules_obs`` / ``rules_jit`` /
``rules_locks`` / ``rules_faults``.

Vocabulary (see DESIGN.md §11 for the full contract):

* **Rule** — a plugin with a stable ``id``, a ``severity``, a path-prefix
  ``scope``, and a reviewed ``allowlist`` of ``file`` or ``file::function``
  keys.  Per-file findings come from :meth:`Rule.check`; cross-file
  analyses (fault-site coverage) report from :meth:`Rule.finalize` after
  every file has been scanned.
* **Suppression** — ``# lint: disable=<rule-id>[,<rule-id>...]`` on the
  flagged line silences exactly that line; ``disable=all`` silences every
  rule there.  Suppressions are counted, never silent.
* **Baseline** — ``audits/lint_baseline.json`` grandfathers reviewed
  findings by ``rule::path::function`` key with a per-key count and a
  mandatory reason.  Baselined findings are reported but do not fail the
  run; ratchet mode (``--ratchet``) additionally fails when any rule's
  total finding count exceeds its committed baseline total, so the
  grandfathered set can only shrink.
"""
from __future__ import annotations

import ast
import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*disable=([a-zA-Z0-9_,\- ]+)")

#: Default committed-baseline location, repo-relative.
BASELINE_REL = "audits/lint_baseline.json"


@dataclass(frozen=True)
class Finding:
    """One violation: where, which rule, and the actionable message."""

    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    function: str  # enclosing def/class attribution ('<module>' at top level)
    message: str
    severity: str = "error"

    @property
    def key(self) -> str:
        """Line-number-free identity used for baseline matching (line churn
        from unrelated edits must not invalidate a grandfathered entry)."""
        return f"{self.rule}::{self.path}::{self.function}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "function": self.function, "severity": self.severity,
                "message": self.message}


_EMPTY_TARGETS: frozenset = frozenset()


def attributed_nodes(tree: ast.AST) -> List[tuple]:
    """One shared walk: ``(node, function, in_loop_body, loop_targets)``.

    Attribution and loop context are scope-accurate:

    * nested ``def``/``lambda`` resets the loop context (a decode closure
      defined inside a function and *called* from a loop is the pipeline's
      drain path, not a loop-body fetch);
    * a ``ClassDef`` re-attributes its body to the class name — a handler
      in a class body must not inherit the enclosing function's allowlist
      key (methods still attribute to the method name);
    * only per-iteration code is in-loop: a ``for``/``while`` ``else:``
      clause and a ``for``'s iterable run once and keep the outer context
      (a ``while``'s test re-evaluates per iteration, so it counts);
    * ``loop_targets`` is the set of iteration-variable names of every
      enclosing ``for`` in the same function scope.

    Every rule iterates this one cached list (via
    :meth:`FileContext.attributed`) instead of re-walking the tree.

    Iterative (explicit stack) with direct ``__dict__`` child iteration —
    the walk runs once per file over the whole repo and is the engine's
    hot loop; node order within the list is unspecified (the engine sorts
    findings by location at the end).
    """
    AST = ast.AST
    out: List[tuple] = []
    app = out.append
    stack: List[tuple] = [(tree, "<module>", False, _EMPTY_TARGETS)]
    pop = stack.pop
    push = stack.append
    while stack:
        item = pop()
        app(item)
        node, fn, in_loop, targets = item
        cls = node.__class__
        if cls is ast.FunctionDef or cls is ast.AsyncFunctionDef:
            fn, in_loop, targets = node.name, False, _EMPTY_TARGETS
        elif cls is ast.Lambda:
            in_loop, targets = False, _EMPTY_TARGETS
        elif cls is ast.ClassDef:
            fn = node.name
        elif cls is ast.For or cls is ast.AsyncFor:
            inner = targets | frozenset(
                n.id for n in ast.walk(node.target)
                if n.__class__ is ast.Name)
            push((node.target, fn, in_loop, targets))
            push((node.iter, fn, in_loop, targets))
            for child in node.body:
                push((child, fn, True, inner))
            for child in node.orelse:
                push((child, fn, in_loop, targets))
            continue
        elif cls is ast.While:
            push((node.test, fn, True, targets))
            for child in node.body:
                push((child, fn, True, targets))
            for child in node.orelse:
                push((child, fn, in_loop, targets))
            continue
        for v in node.__dict__.values():
            if v.__class__ is list:
                for it in v:
                    if isinstance(it, AST):
                        push((it, fn, in_loop, targets))
            elif isinstance(v, AST):
                push((v, fn, in_loop, targets))
    return out


class FileContext:
    """Parsed view of one file: AST, source lines, per-line suppressions.

    ``cache`` is a per-file scratch dict rules share derived analyses
    through (e.g. the jitted-def discovery both jit rules need).
    """

    def __init__(self, path: str, rel: str, src: Optional[str] = None):
        if src is None:
            with open(path) as fp:
                src = fp.read()
        self.path = path
        self.rel = rel
        self.src = src
        self.tree = ast.parse(src, filename=path)
        self.cache: Dict[str, object] = {}
        self._attributed: Optional[List[tuple]] = None
        self._suppress: Dict[int, set] = {}
        for i, line in enumerate(src.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
                self._suppress[i] = ids

    def attributed(self) -> List[tuple]:
        """Cached :func:`attributed_nodes` of this file's tree."""
        if self._attributed is None:
            self._attributed = attributed_nodes(self.tree)
        return self._attributed

    def suppressed(self, line: int, rule_id: str) -> bool:
        ids = self._suppress.get(line)
        return bool(ids) and (rule_id in ids or "all" in ids)

    def suppressions(self) -> Dict[int, set]:
        return dict(self._suppress)


class Rule:
    """Plugin protocol (subclass, set the class attrs, implement check).

    ``scope`` is a tuple of repo-relative path prefixes; the engine calls
    :meth:`check` only for files inside it.  ``allowlist`` entries are
    either a repo-relative file path (whole file exempt) or
    ``path::function`` (one attribution key exempt) — reviewed exceptions,
    each of which should carry a reason comment where it is defined.
    """

    id: str = ""
    severity: str = "error"
    description: str = ""
    scope: Tuple[str, ...] = ("fairify_tpu/",)
    allowlist: frozenset = frozenset()

    def applies(self, rel: str) -> bool:
        return rel.startswith(tuple(self.scope))

    def allowed(self, rel: str, function: str = "<module>") -> bool:
        return rel in self.allowlist or f"{rel}::{function}" in self.allowlist

    def finding(self, ctx: FileContext, line: int, message: str,
                function: str = "<module>") -> Finding:
        return Finding(rule=self.id, path=ctx.rel, line=line,
                       function=function, message=message,
                       severity=self.severity)

    def check(self, ctx: FileContext) -> Iterable[Finding]:  # per-file
        return ()

    def finalize(self, files: Dict[str, FileContext]) -> Iterable[Finding]:
        """Cross-file findings, called once after every file's check()."""
        return ()


@dataclass
class LintResult:
    """Everything a renderer or CI gate needs from one engine run."""

    findings: List[Finding] = field(default_factory=list)  # actionable
    baselined: List[Finding] = field(default_factory=list)  # grandfathered
    suppressed: int = 0
    suppressed_by_rule: Dict[str, int] = field(default_factory=dict)
    parse_errors: List[Finding] = field(default_factory=list)
    rules: List[str] = field(default_factory=list)
    n_files: int = 0
    duration_s: float = 0.0
    ratchet_breaches: List[str] = field(default_factory=list)

    def counts(self, include_baselined: bool = False) -> Dict[str, int]:
        out = {r: 0 for r in self.rules}
        pools = [self.findings] + ([self.baselined] if include_baselined else [])
        for pool in pools:
            for f in pool:
                out[f.rule] = out.get(f.rule, 0) + 1
        return out

    @property
    def ok(self) -> bool:
        return not self.findings and not self.parse_errors \
            and not self.ratchet_breaches

    def as_dict(self) -> dict:
        return {
            "version": 1,
            "ok": self.ok,
            "rules": list(self.rules),
            "n_files": self.n_files,
            "duration_s": round(self.duration_s, 4),
            "counts": self.counts(),
            "findings": [f.as_dict() for f in self.findings],
            "baselined": [f.as_dict() for f in self.baselined],
            "parse_errors": [f.as_dict() for f in self.parse_errors],
            "suppressed": self.suppressed,
            "suppressed_by_rule": dict(sorted(
                self.suppressed_by_rule.items())),
            "ratchet_breaches": list(self.ratchet_breaches),
        }


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, dict]:
    """``rule::path::function`` → ``{"count": n, "reason": str}``.

    A missing file is an empty baseline (the committed tree should be
    clean); a malformed one raises — a broken gate must be loud.
    """
    if not os.path.exists(path):
        return {}
    with open(path) as fp:
        doc = json.load(fp)
    findings = doc.get("findings", {})
    out = {}
    for key, ent in findings.items():
        if not isinstance(ent, dict) or int(ent.get("count", 0)) < 1:
            raise ValueError(f"baseline entry {key!r} needs a count >= 1")
        if not str(ent.get("reason", "")).strip():
            raise ValueError(
                f"baseline entry {key!r} needs a non-empty reason")
        out[key] = {"count": int(ent["count"]),
                    "reason": str(ent["reason"])}
    return out


def apply_baseline(findings: List[Finding], baseline: Dict[str, dict]
                   ) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (actionable, baselined) under per-key budgets."""
    budget = {k: v["count"] for k, v in baseline.items()}
    active, grandfathered = [], []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            grandfathered.append(f)
        else:
            active.append(f)
    return active, grandfathered


def ratchet_breaches(result: LintResult, baseline: Dict[str, dict]
                     ) -> List[str]:
    """Per-rule totals (active + baselined) vs the committed baseline totals.

    Any rule whose finding count exceeds its baseline total is a breach —
    the grandfathered set may only shrink.
    """
    base_totals: Dict[str, int] = {}
    for key, ent in baseline.items():
        rule = key.split("::", 1)[0]
        base_totals[rule] = base_totals.get(rule, 0) + ent["count"]
    breaches = []
    for rule, n in sorted(result.counts(include_baselined=True).items()):
        allowed = base_totals.get(rule, 0)
        if n > allowed:
            breaches.append(f"{rule}: {n} finding(s) > baseline {allowed}")
    return breaches


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


def repo_root() -> str:
    """The repo checkout this package lives in (…/fairify_tpu/lint/core.py)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_py_files(root: str, subdir: str = "fairify_tpu"
                  ) -> Iterable[Tuple[str, str]]:
    """Sorted (abs path, repo-relative path) for every .py under subdir."""
    base = os.path.join(root, subdir)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                yield path, os.path.relpath(path, root).replace(os.sep, "/")


def default_files(root: str) -> List[Tuple[str, str]]:
    """The default whole-repo file set: ``fairify_tpu/`` plus ``scripts/``.

    Scripts are walked so cross-file rules can see the harness side of a
    contract (``chaos-coverage`` reads scripts/chaos_matrix.py); rules
    scoped to ``fairify_tpu/`` simply skip them via :meth:`Rule.applies`.
    """
    files = list(iter_py_files(root))
    if os.path.isdir(os.path.join(root, "scripts")):
        files += list(iter_py_files(root, "scripts"))
    return files


def run_lint(root: Optional[str] = None,
             rules: Optional[Sequence[Rule]] = None,
             files: Optional[Sequence[Tuple[str, str]]] = None,
             baseline: Optional[Dict[str, dict]] = None,
             ratchet: bool = False) -> LintResult:
    """Run ``rules`` over the repo (or an explicit ``files`` list).

    ``files`` entries are ``(abs_path, repo_relative)`` — the fixture
    corpus uses this to lint virtual trees.  ``baseline`` is the loaded
    grandfather map (``None`` = empty).
    """
    t0 = time.perf_counter()
    if rules is None:
        from fairify_tpu.lint.rules import all_rules

        rules = all_rules()
    if root is None:
        root = repo_root()
    if files is None:
        files = default_files(root)

    result = LintResult(rules=[r.id for r in rules])
    contexts: Dict[str, FileContext] = {}
    raw: List[Finding] = []
    for path, rel in files:
        try:
            ctx = FileContext(path, rel)
        except SyntaxError as exc:
            result.parse_errors.append(Finding(
                rule="parse", path=rel, line=exc.lineno or 0,
                function="<module>", message=f"syntax error: {exc.msg}"))
            continue
        contexts[rel] = ctx
        for rule in rules:
            if rule.applies(rel):
                raw.extend(rule.check(ctx))
    for rule in rules:
        raw.extend(rule.finalize(contexts))

    kept: List[Finding] = []
    for f in raw:
        ctx = contexts.get(f.path)
        if ctx is not None and ctx.suppressed(f.line, f.rule):
            result.suppressed += 1
            result.suppressed_by_rule[f.rule] = \
                result.suppressed_by_rule.get(f.rule, 0) + 1
        else:
            kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))

    result.findings, result.baselined = apply_baseline(kept, baseline or {})
    result.n_files = len(contexts)
    if ratchet:
        result.ratchet_breaches = ratchet_breaches(result, baseline or {})
    result.duration_s = time.perf_counter() - t0
    return result


# ---------------------------------------------------------------------------
# Rendering + CLI
# ---------------------------------------------------------------------------


def render_text(result: LintResult, verbose_baselined: bool = False) -> str:
    lines = []
    for f in result.parse_errors:
        lines.append(f.render())
    for f in result.findings:
        lines.append(f.render())
    if verbose_baselined:
        for f in result.baselined:
            lines.append(f"{f.render()}  (baselined)")
    for b in result.ratchet_breaches:
        lines.append(f"ratchet: {b}")
    if result.suppressed_by_rule:
        per = ", ".join(f"{r}={n}" for r, n in
                        sorted(result.suppressed_by_rule.items()))
        lines.append(f"suppressed by rule: {per}")
    n = len(result.findings) + len(result.parse_errors)
    lines.append(
        f"lint: {n} finding(s), {len(result.baselined)} baselined, "
        f"{result.suppressed} suppressed — {len(result.rules)} rules over "
        f"{result.n_files} files in {result.duration_s:.2f}s")
    return "\n".join(lines)


def add_cli_args(ap) -> None:
    """Lint CLI options, defined once — used by this module's ``main`` and
    by the ``fairify_tpu lint`` subparser (``cli._cmd_lint`` forwards its
    parsed namespace straight to :func:`run_cli`)."""
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--ir", action="store_true",
                    help="run the jaxpr/IR-level passes over the obs_jit "
                         "kernel registry instead of the AST rules "
                         "(imports jax and lowers every kernel; see "
                         "DESIGN.md §11 'IR-level passes')")
    ap.add_argument("--ratchet", action="store_true",
                    help="also fail if any rule's finding count exceeds the "
                         "committed baseline total (growth gate)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline JSON path (default <root>/{BASELINE_REL}; "
                         f"'none' disables)")
    ap.add_argument("--root", default=None, help="repo root override")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule-id subset (default: every "
                         "rule of the active mode)")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print grandfathered findings (text format)")


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI shared by ``fairify_tpu lint`` and ``scripts/lint.py``."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="fairify_tpu lint",
        description="static-analysis rule engine over fairify_tpu/: AST "
                    "rules by default, jaxpr/IR passes over the obs_jit "
                    "registry with --ir (see DESIGN.md §11)")
    add_cli_args(ap)
    return run_cli(ap.parse_args(argv))


def run_cli(args) -> int:
    """Run the engine from a parsed :func:`add_cli_args` namespace."""
    import sys

    root = args.root or repo_root()
    if getattr(args, "ir", False):
        # Deferred import: the IR suite needs jax + the kernel modules;
        # the AST engine must stay importable without either.
        from fairify_tpu.analysis.irlint import ir_rules

        rules = ir_rules()
    else:
        from fairify_tpu.lint.rules import all_rules

        rules = all_rules()
    if args.rules:
        want = {s.strip() for s in args.rules.split(",") if s.strip()}
        unknown = want - {r.id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {sorted(unknown)} "
                  f"(known: {sorted(r.id for r in rules)})", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in want]

    baseline: Dict[str, dict] = {}
    if args.baseline != "none":
        bpath = args.baseline or os.path.join(root, BASELINE_REL)
        try:
            baseline = load_baseline(bpath)
        except (ValueError, json.JSONDecodeError) as exc:
            print(f"bad baseline {bpath}: {exc}", file=sys.stderr)
            return 2

    result = run_lint(root=root, rules=rules, baseline=baseline,
                      ratchet=args.ratchet)
    if args.format == "json":
        print(json.dumps(result.as_dict(), indent=2))
    else:
        print(render_text(result, verbose_baselined=args.show_baselined))
    return 0 if result.ok else 1

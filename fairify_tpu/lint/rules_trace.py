"""The distributed-tracing propagation rule (DESIGN.md §19).

Every request-scoped JSON frame that crosses a process boundary — a
newline-framed pipe message (``protocol.dump_msg`` / hand-rolled
``json.dumps(...) + "\\n"``) or an atomic spool payload
(``write_atomic_json``) — must carry the trace fields (``trace`` /
``trace_id``) that join the per-process shards into one trace tree.  A
frame writer that drops them silently severs the tree: the request still
completes, but ``fairify_tpu report --trace-dir`` can no longer attribute
its critical path, which is exactly the failure mode a lint (not a test)
has to guard — nothing crashes.

The rule is deliberately *provable-absence only*: it flags a frame
expression **only when it is a dict literal** that demonstrably lacks
trace fields and is not a control frame.  Everything it cannot decide is
skipped, so the rule has no false positives by construction:

* a bare-``Name`` frame that is a **parameter** of the enclosing function
  is a pass-through writer (``def send(obj): pipe.write(dump_msg(obj))``)
  — the frame *constructor* is the responsible party, and the rule fires
  there instead;
* any other non-literal frame (a payload loaded from disk and forwarded
  verbatim, a locally assembled record) is opaque to the AST and skipped;
* a literal with a ``**spread`` may carry trace through the spread.

Control frames are exempt by a reviewed vocabulary, not per-site
allowlist entries: frames whose ``op`` is in :data:`CONTROL_OPS`
(ping/pong/drain/metrics/… — fleet plumbing with no request identity) or
that carry a :data:`CONTROL_KEYS` discriminator (``hello``/``pong``/
``fatal``/``error`` responses).  Growing either set is the review point,
same contract as the allowlists in ``rules_obs``.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from fairify_tpu.lint.core import FileContext, Finding, Rule

#: Request-identity fields that join a frame to its trace tree.
TRACE_KEYS = frozenset({"trace", "trace_id"})

#: Reviewed control-frame vocabulary: ``op`` values with no request
#: identity to propagate (fleet/worker lifecycle plumbing).  A new op
#: added here is a review decision — per-request ops (``solve``) must
#: NOT appear.
CONTROL_OPS = frozenset({
    "ping", "pong", "hello", "exit", "drain", "drained", "dead",
    "ready", "status", "metrics", "hang", "memout",
})

#: Frame discriminators that mark a control/diagnostic response on their
#: own (the worker's response channel has no ``op`` field): handshake,
#: liveness, and fatal/error frames emitted outside any request context.
CONTROL_KEYS = frozenset({"hello", "pong", "ping", "fatal", "error"})

#: ``file`` / ``file::function`` reviewed exceptions (empty: the whole
#: tree is compliant; a new entry needs a reason in review).
ALLOW_TRACE_CONTEXT: frozenset = frozenset()

#: Callables whose argument IS a cross-boundary frame.
_FRAME_FNS = frozenset({"dump_msg"})           # frame = arg 0
_SPOOL_FNS = frozenset({"write_atomic_json", "_atomic_json"})  # frame = arg 1
#: Send-helper names: judged only when handed a dict literal directly
#: (a Name argument is the pass-through idiom, handled at its source).
_SEND_FNS = frozenset({"send", "_send", "respond", "_respond"})

_HINT = (
    "cross-process JSON frame without trace fields — request-scoped "
    "frames must carry the submit-stamped trace context ({'trace': "
    "obs.trace.context_fields()['trace']} or a 'trace_id') so the "
    "per-process shards join into one tree (DESIGN.md §19); control "
    "frames belong in rules_trace.CONTROL_OPS/CONTROL_KEYS, reviewed "
    "exceptions in ALLOW_TRACE_CONTEXT")


def _callee_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_json_dumps(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "dumps"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "json")


def _newline_framed_dumps(node: ast.BinOp) -> Optional[ast.Call]:
    """``json.dumps(x) + "\\n"`` (either operand order) → the dumps call."""
    if not isinstance(node.op, ast.Add):
        return None
    for a, b in ((node.left, node.right), (node.right, node.left)):
        if _is_json_dumps(a) and isinstance(b, ast.Constant) \
                and isinstance(b.value, str) and "\n" in b.value:
            return a
    return None


def _dict_lacks_trace(d: ast.Dict) -> bool:
    """True only when the literal PROVABLY lacks trace fields and is not
    a control frame — ``**spread`` keys make it undecidable (pass)."""
    keys = []
    for k, v in zip(d.keys, d.values):
        if k is None:
            return False  # **spread: may carry trace
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.append((k.value, v))
    names = {k for k, _ in keys}
    if names & TRACE_KEYS or names & CONTROL_KEYS:
        return False
    for k, v in keys:
        if k == "op" and isinstance(v, ast.Constant) \
                and v.value in CONTROL_OPS:
            return False
    return True


class TraceContextRule(Rule):
    """Flag cross-process frame writes whose payload provably drops the
    distributed-trace context."""

    id = "trace-context"
    description = ("cross-process JSON frames (pipe messages, spool "
                   "payloads) must carry trace fields or be reviewed "
                   "control frames — a dropped context severs the merged "
                   "trace tree (DESIGN.md §19)")
    allowlist = ALLOW_TRACE_CONTEXT

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if self.allowed(ctx.rel):
            return []
        out: List[Finding] = []
        self._scan(ctx, ctx.tree, "<module>", out)
        return out

    def _scan(self, ctx: FileContext, node: ast.AST, fn_name: str,
              out: List[Finding]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            fn_name = node.name
        frame: Optional[ast.AST] = None
        if isinstance(node, ast.Call):
            name = _callee_name(node.func)
            args = node.args
            if name in _FRAME_FNS and args:
                frame = args[0]
            elif name in _SPOOL_FNS and len(args) >= 2:
                frame = args[1]
            elif name in _SEND_FNS and len(args) == 1 \
                    and isinstance(args[0], ast.Dict):
                frame = args[0]
        elif isinstance(node, ast.BinOp):
            dumps = _newline_framed_dumps(node)
            if dumps is not None and dumps.args:
                frame = dumps.args[0]
        if frame is not None and not self.allowed(ctx.rel, fn_name):
            # Only a dict literal is judged: a bare-Name frame is either
            # the pass-through-writer idiom (a parameter, responsibility
            # at the frame constructor) or an opaque local — absence is
            # unprovable either way, so no finding (module docstring).
            if isinstance(frame, ast.Dict) and _dict_lacks_trace(frame):
                out.append(self.finding(
                    ctx, getattr(frame, "lineno", node.lineno), _HINT,
                    function=fn_name))
        for child in ast.iter_child_nodes(node):
            self._scan(ctx, child, fn_name, out)

"""The shipped rule set: fifteen rules.

Rule ids are stable API — inline suppressions, allowlists, and the
committed baseline all key on them:

==========================  ================================================
id                          guards
==========================  ================================================
``obs-time-time``           wall-clock timing outside PhaseTimer/obs spans
``obs-print``               progress/diagnostics bypassing the heartbeat
``obs-raw-jit``             device kernels not registered through obs_jit
``obs-broad-except``        swallowed faults the resilience layer never
                            saw; BaseException handlers that could eat a
                            kill/interrupt
``obs-loop-fetch``          sync device fetches stalling the launch queue
``jit-purity``              trace-time side effects inside jitted bodies
``recompile-hazard``        static-arg/signature churn → silent recompiles
``lock-discipline``         lock-protected attrs accessed without the lock
``fault-site-coverage``     chaos sites drifting from their call sites
``chaos-coverage``          registered sites drifting from the chaos
                            matrix (scripts/chaos_matrix.py cells)
``lock-order``              cycles in the whole-program lock graph
``blocking-under-lock``     blocking calls reached while a lock is held
``kill-safety``             torn-state hazards around kill/yield points
``cv-discipline``           Condition wait/notify misuse
``trace-context``           cross-process JSON frames dropping the
                            distributed-trace context (DESIGN.md §19)
==========================  ================================================

The last four share one whole-program analysis per run
(:mod:`fairify_tpu.analysis.locks` via ``rules_concurrency``), which is
also the static ground truth the dynamic lockprof cross-check
(:mod:`fairify_tpu.obs.lockprof`) verifies observed edges against.

To add a rule: subclass :class:`fairify_tpu.lint.core.Rule` in a
``rules_*`` module, give it a stable id/scope/description, add it to
:func:`all_rules`, and ship ≥1 positive and ≥1 negative fixture under
``tests/lint_fixtures/<rule-id>/`` — ``tests/test_lint.py``'s meta-test
fails otherwise.  See DESIGN.md §11 and §16.
"""
from __future__ import annotations

from typing import List

from fairify_tpu.lint.core import Rule
from fairify_tpu.lint.rules_concurrency import concurrency_rules
from fairify_tpu.lint.rules_faults import ChaosCoverageRule, FaultSiteRule
from fairify_tpu.lint.rules_jit import JitPurityRule, RecompileHazardRule
from fairify_tpu.lint.rules_locks import LockDisciplineRule
from fairify_tpu.lint.rules_obs import (
    BroadExceptRule,
    LoopFetchRule,
    PrintRule,
    RawJitRule,
    TimeTimeRule,
)
from fairify_tpu.lint.rules_trace import TraceContextRule

LEGACY_RULE_IDS = ("obs-time-time", "obs-print", "obs-raw-jit",
                   "obs-broad-except", "obs-loop-fetch")


def legacy_rules() -> List[Rule]:
    """The five original observability rules (PR 1–4 era), kept as a
    named subset for targeted runs."""
    return [TimeTimeRule(), PrintRule(), RawJitRule(), BroadExceptRule(),
            LoopFetchRule()]


def all_rules() -> List[Rule]:
    """Fresh instances of every shipped rule (engine runs are stateful —
    cross-file rules accumulate during check and report in finalize)."""
    return legacy_rules() + [JitPurityRule(), RecompileHazardRule(),
                             LockDisciplineRule(), FaultSiteRule(),
                             ChaosCoverageRule()] + concurrency_rules() \
        + [TraceContextRule()]

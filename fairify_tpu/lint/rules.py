"""The shipped rule set: nine rules, five migrated + four new.

Rule ids are stable API — inline suppressions, allowlists, and the
committed baseline all key on them:

==========================  ================================================
id                          guards
==========================  ================================================
``obs-time-time``           wall-clock timing outside PhaseTimer/obs spans
``obs-print``               progress/diagnostics bypassing the heartbeat
``obs-raw-jit``             device kernels not registered through obs_jit
``obs-broad-except``        swallowed faults the resilience layer never saw
``obs-loop-fetch``          sync device fetches stalling the launch queue
``jit-purity``              trace-time side effects inside jitted bodies
``recompile-hazard``        static-arg/signature churn → silent recompiles
``lock-discipline``         lock-protected attrs accessed without the lock
``fault-site-coverage``     chaos sites drifting from their call sites
==========================  ================================================

To add a rule: subclass :class:`fairify_tpu.lint.core.Rule` in a
``rules_*`` module, give it a stable id/scope/description, add it to
:func:`all_rules`, and ship ≥1 positive and ≥1 negative fixture under
``tests/lint_fixtures/<rule-id>/`` — ``tests/test_lint.py``'s meta-test
fails otherwise.  See DESIGN.md §11.
"""
from __future__ import annotations

from typing import List

from fairify_tpu.lint.core import Rule
from fairify_tpu.lint.rules_faults import FaultSiteRule
from fairify_tpu.lint.rules_jit import JitPurityRule, RecompileHazardRule
from fairify_tpu.lint.rules_locks import LockDisciplineRule
from fairify_tpu.lint.rules_obs import (
    BroadExceptRule,
    LoopFetchRule,
    PrintRule,
    RawJitRule,
    TimeTimeRule,
)

LEGACY_RULE_IDS = ("obs-time-time", "obs-print", "obs-raw-jit",
                   "obs-broad-except", "obs-loop-fetch")


def legacy_rules() -> List[Rule]:
    """The five rules ``scripts/lint_obs.py`` shipped (shim surface)."""
    return [TimeTimeRule(), PrintRule(), RawJitRule(), BroadExceptRule(),
            LoopFetchRule()]


def all_rules() -> List[Rule]:
    """Fresh instances of every shipped rule (engine runs are stateful —
    cross-file rules accumulate during check and report in finalize)."""
    return legacy_rules() + [JitPurityRule(), RecompileHazardRule(),
                             LockDisciplineRule(), FaultSiteRule()]

"""Fault-site coverage: the chaos registry and its call sites never drift.

``resilience/faults.py`` names the places the sweep talks to something
that can die (``FAULT_SITES``); the chaos suite's guarantees are only as
strong as those names staying wired.  Two failure modes, both silent at
runtime:

* a call site passes a site string the registry doesn't know — every
  ``--inject-fault`` spec for it is rejected at the CLI while the code
  path runs unprotected (no schedule can ever fire there);
* a registered site loses its last call site in a refactor — the chaos
  matrix keeps "covering" a place the sweep no longer visits.

The rule collects every **literal** site string across ``fairify_tpu/``
from (a) ``faults.check("<site>")`` calls (module aliases ``faults`` /
``faults_mod``) and (b) ``fault_site="<site>"`` keyword arguments (the
:class:`resilience.journal.JournalWriter` contract).  Dynamic site
expressions (``faults.check(self._site)``) are invisible to the AST and
intentionally uncounted — each site must keep at least one literal
anchor.  ``supervisor.run(..., site=...)`` labels are classification
metadata, not injection sites, and are ignored.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from fairify_tpu.lint.core import FileContext, Finding, Rule

#: The registry module, repo-relative (where FAULT_SITES is declared).
FAULTS_REL = "fairify_tpu/resilience/faults.py"

#: The chaos driver, repo-relative (the default lint walk includes
#: ``scripts/`` precisely so the coverage rule can see it).
CHAOS_REL = "scripts/chaos_matrix.py"

_CHECK_ALIASES = frozenset({"faults", "faults_mod"})


def _fault_sites_decl(tree: ast.AST) -> Optional[Tuple[frozenset, int]]:
    """(sites, lineno) of the ``FAULT_SITES = frozenset({...})`` literal."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "FAULT_SITES"
                   for t in node.targets):
            continue
        strings = [n.value for n in ast.walk(node.value)
                   if isinstance(n, ast.Constant)
                   and isinstance(n.value, str)]
        return frozenset(strings), node.lineno
    return None


def _literal_strings(expr: ast.AST) -> Iterable[ast.Constant]:
    """String constants in ``expr`` that are whole site names: a bare
    literal or a literal arm of a default pattern (``site or "x"``,
    ternary).  f-string fragments (``f"ledger.{op}"``) are *pieces* of a
    dynamic site, not sites — walking into JoinedStr would turn the
    documented literal-anchor contract into false positives."""
    stack = [expr]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.JoinedStr):
            continue
        if isinstance(n, ast.Constant):
            if isinstance(n.value, str):
                yield n
        else:
            stack.extend(ast.iter_child_nodes(n))


class FaultSiteRule(Rule):
    id = "fault-site-coverage"
    description = ("every literal site passed to resilience.faults.check "
                   "must be registered in FAULT_SITES, and every "
                   "registered site must keep >=1 literal call site")

    def __init__(self):
        # (site, rel, line) of every literal use seen this run.
        self._uses: List[Tuple[str, str, int]] = []

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel == FAULTS_REL:
            return ()  # the registry declares sites; it doesn't consume them
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "check" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in _CHECK_ALIASES:
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    self._uses.append((node.args[0].value, ctx.rel,
                                       node.lineno))
            for kw in node.keywords:
                if kw.arg == "fault_site":
                    for sub in _literal_strings(kw.value):
                        self._uses.append((sub.value, ctx.rel, sub.lineno))
        return ()

    def finalize(self, files: Dict[str, FileContext]) -> Iterable[Finding]:
        reg = files.get(FAULTS_REL)
        decl = _fault_sites_decl(reg.tree) if reg is not None else None
        if decl is None:
            # No registry in this file set (partial runs/fixtures without
            # one): nothing to validate against.
            return
        sites, decl_line = decl
        covered = set()
        for site, rel, line in self._uses:
            covered.add(site)
            if site not in sites:
                yield Finding(
                    rule=self.id, path=rel, line=line, function="<module>",
                    message=(f"unknown fault site {site!r} — not in "
                             f"resilience.faults.FAULT_SITES "
                             f"({sorted(sites)}); injection specs for it "
                             f"are rejected and the path runs unprotected"),
                    severity=self.severity)
        for site in sorted(sites - covered):
            yield Finding(
                rule=self.id, path=FAULTS_REL, line=decl_line,
                function="<module>",
                message=(f"registered fault site {site!r} has no literal "
                         f"call site in fairify_tpu/ — chaos coverage for "
                         f"it is silently disabled; call faults.check"
                         f"({site!r}) at the site or retire the entry"),
                severity=self.severity)


# ---------------------------------------------------------------------------
# Chaos-matrix coverage: the registry and the chaos driver never drift
# ---------------------------------------------------------------------------

#: Sites reviewed as covered OUTSIDE scripts/chaos_matrix.py.  Every entry
#: needs the test/driver that actually exercises it; a stale entry (site
#: retired, or a chaos cell later added) is itself a finding.
CHAOS_EXEMPT = {
    # smt.query earned matrix cells in the --integrity section (the
    # corrupt-witness cells ride the brute fallback solver, no z3
    # needed), so its old z3-gated exemption is gone.
    # Sharded-runtime dispatch/gather faults are exercised by the sharded
    # chaos tests in tests/test_resilience.py (sharded-vs-plain
    # bit-equality, interleaved shard journals); the matrix covers the
    # user-visible shard fault surface via its device.lost cells.
    "shard.dispatch": "sharded chaos tests in tests/test_resilience.py",
    "shard.gather": "sharded chaos tests in tests/test_resilience.py",
}

#: A full injection spec literal: site:kind:nth (kind vocabulary pinned so
#: arbitrary colon-bearing strings never match; ``corrupt`` is the
#: bit-flip kind of the result-integrity layer, DESIGN.md §21).  The
#: ``:nth`` tail is required — degrade *reasons* reuse the ``site:kind``
#: shape (``integrity.launch.decode:fatal``) and must not count as cells.
_SPEC_RE = re.compile(r"^([a-z][a-z._]*):(transient|fatal|crash|corrupt):\d+\+?$")
#: An f-string site fragment: the literal head of f"{site}:..." style specs.
_FRAG_RE = re.compile(r"^([a-z][a-z._]*):")


def _chaos_sites(tree: ast.AST, known: frozenset
                 ) -> Tuple[Set[str], List[Tuple[str, int]]]:
    """(covered sites, [(unknown spec site, line)]) from the chaos driver.

    Coverage counts (a) full ``site:kind:nth`` string literals, (b) the
    literal head fragment of an f-string spec (``f"device.lost:{kind}:…"``),
    and (c) bare site-name literals (the site lists the SMT section loops
    over).  A full spec naming an unregistered site is reported — the
    driver would crash or silently no-op on it.
    """
    covered: Set[str] = set()
    unknown: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Constant) and isinstance(node.value, str)):
            continue
        text = node.value
        if text in known:
            covered.add(text)
            continue
        m = _SPEC_RE.match(text)
        if m:
            if m.group(1) in known:
                covered.add(m.group(1))
            else:
                unknown.append((m.group(1), node.lineno))
            continue
        m = _FRAG_RE.match(text)
        if m and m.group(1) in known:
            covered.add(m.group(1))
    return covered, unknown


class ChaosCoverageRule(Rule):
    id = "chaos-coverage"
    description = ("every registered fault site needs >=1 chaos-matrix "
                   "cell (a literal spec in scripts/chaos_matrix.py) or a "
                   "documented CHAOS_EXEMPT entry")
    scope = (FAULTS_REL, CHAOS_REL)

    def finalize(self, files: Dict[str, FileContext]) -> Iterable[Finding]:
        reg = files.get(FAULTS_REL)
        chaos = files.get(CHAOS_REL)
        decl = _fault_sites_decl(reg.tree) if reg is not None else None
        if decl is None or chaos is None:
            # Partial runs/fixture sets without both halves: nothing to
            # validate against.
            return
        sites, decl_line = decl
        covered, unknown = _chaos_sites(chaos.tree, sites)
        for site, line in unknown:
            yield Finding(
                rule=self.id, path=CHAOS_REL, line=line,
                function="<module>",
                message=(f"chaos cell references unknown fault site "
                         f"{site!r} — not in resilience.faults.FAULT_SITES; "
                         f"the spec is rejected at arm time and the cell "
                         f"can never fire"), severity=self.severity)
        for site in sorted(sites):
            if site in covered:
                continue
            if site in CHAOS_EXEMPT:
                continue
            yield Finding(
                rule=self.id, path=FAULTS_REL, line=decl_line,
                function="<module>",
                message=(f"registered fault site {site!r} has no "
                         f"scripts/chaos_matrix.py cell and no CHAOS_EXEMPT "
                         f"entry — the registry and the chaos matrix have "
                         f"drifted; add a cell or document the exemption "
                         f"with the test that covers it"),
                severity=self.severity)
        for site, why in sorted(CHAOS_EXEMPT.items()):
            if site not in sites:
                yield Finding(
                    rule=self.id, path=FAULTS_REL, line=decl_line,
                    function="<module>",
                    message=(f"stale CHAOS_EXEMPT entry {site!r} ({why}) — "
                             f"the site is no longer registered; drop the "
                             f"exemption"), severity=self.severity)
            elif site in covered:
                yield Finding(
                    rule=self.id, path=FAULTS_REL, line=decl_line,
                    function="<module>",
                    message=(f"stale CHAOS_EXEMPT entry {site!r} ({why}) — "
                             f"scripts/chaos_matrix.py now has a cell for "
                             f"it; drop the exemption"),
                    severity=self.severity)

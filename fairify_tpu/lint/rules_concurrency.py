"""The four concurrency rules over the whole-program lock graph.

All four ride ONE shared :class:`fairify_tpu.analysis.locks.
ConcurrencyAnalysis` instance per engine run (``concurrency_rules()``
wires the sharing): every rule's ``check()`` feeds the file into the
analysis once, and ``finalize()`` triggers the single global pass —
graph construction, call-site propagation, cycle detection — then each
rule reports its own finding kind.  The same graph is the ground truth
for the dynamic cross-check (:mod:`fairify_tpu.obs.lockprof`).

==========================  ================================================
id                          guards
==========================  ================================================
``lock-order``              a cycle in the global acquisition graph —
                            two threads taking the locks in opposite
                            order deadlock; the finding message carries
                            the full witness path
``blocking-under-lock``     a reviewed registry of blocking calls
                            (sleep/subprocess/device fetch/file I/O/
                            ``Thread.join``/``Future.result``/…) reached
                            while a lock is held, including through
                            call chains — flagged at the call site where
                            the lock is actually held
``kill-safety``             a ``with <lock>`` region with ≥2 guarded
                            mutations around a kill/yield point
                            (``faults.check`` / ``raise ReplicaKilled``)
                            — the kill releases the lock with the
                            invariant half-published; plus manual
                            ``.acquire()`` without try/finally
``cv-discipline``           ``Condition.wait`` outside a while-predicate
                            loop, wait/notify without holding
==========================  ================================================

Allowlist policy is the §11 workflow (fix > suppress > allowlist >
baseline).  The entries below are the reviewed cases where a lock exists
*precisely to serialize* the flagged blocking operation — removing the
lock or moving the operation would break the contract the lock
implements, so the finding is by-design.
"""
from __future__ import annotations

from typing import Dict, Iterable, List

from fairify_tpu.analysis.locks import ConcurrencyAnalysis, RawFinding, _short
from fairify_tpu.lint.core import FileContext, Finding, Rule

#: Reviewed ``file::function`` sites where the held lock's purpose IS to
#: serialize the flagged blocking operation.  Shrink, don't grow.
ALLOW_BLOCKING_UNDER_LOCK = frozenset({
    # Crash-safe JSONL appends: the writer lock exists to serialize
    # append+fsync so records never interleave — fsync under the lock is
    # the contract, not an accident (DESIGN.md §10).
    "fairify_tpu/resilience/journal.py::_append_once",
    # Obs event log: same single-writer append discipline; runs with
    # fsync=False (flush only), invisible to a lexical analysis.
    "fairify_tpu/obs/trace.py::_write",
    # One-time double-checked native-library build: the module lock
    # exists to serialize the g++ build + dlopen across threads; after
    # `_tried` flips the lock is held for a dict read only.
    "fairify_tpu/ops/exact_native.py::_load",
})

ALLOW_LOCK_ORDER: frozenset = frozenset()
ALLOW_KILL_SAFETY: frozenset = frozenset()
ALLOW_CV_DISCIPLINE: frozenset = frozenset()


class _ConcurrencyRule(Rule):
    """Base: feed files into the shared analysis, report one finding kind."""

    def __init__(self, shared: ConcurrencyAnalysis):
        self._shared = shared

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        self._shared.add_file(ctx.rel, ctx.tree)
        return ()

    def finalize(self, files: Dict[str, FileContext]) -> Iterable[Finding]:
        self._shared.finalize()
        for raw in self._raw_findings():
            if self.allowed(raw.rel, raw.function):
                continue
            yield Finding(rule=self.id, path=raw.rel, line=raw.line,
                          function=raw.function, message=raw.message,
                          severity=self.severity)

    def _raw_findings(self) -> List[RawFinding]:
        return []


class LockOrderRule(_ConcurrencyRule):
    id = "lock-order"
    description = ("cycle in the whole-program lock-acquisition graph — "
                   "threads taking the locks in opposite order deadlock; "
                   "establish one global order (DESIGN.md §16)")
    allowlist = ALLOW_LOCK_ORDER

    def _raw_findings(self) -> List[RawFinding]:
        out: List[RawFinding] = []
        for cycle in self._shared.cycles():
            path = " -> ".join(
                f"{_short(dst)} ({w.render()})" for _src, dst, w in cycle)
            src0, _dst0, w0 = cycle[0]
            out.append(RawFinding(
                w0.rel, w0.line, w0.function.rsplit(".", 1)[-1],
                f"lock-order cycle: {_short(src0)} -> {path} — potential "
                f"deadlock; acquire these locks in one global order "
                f"everywhere (lock catalog: DESIGN.md §16)"))
        return out


class BlockingUnderLockRule(_ConcurrencyRule):
    id = "blocking-under-lock"
    description = ("registry-listed blocking call (sleep/subprocess/device "
                   "fetch/file I/O/join/result) reached while a lock is "
                   "held, directly or through calls")
    allowlist = ALLOW_BLOCKING_UNDER_LOCK

    def _raw_findings(self) -> List[RawFinding]:
        return self._shared.blocking


class KillSafetyRule(_ConcurrencyRule):
    id = "kill-safety"
    description = ("lock-guarded region unsafe under ReplicaKilled/fault "
                   "injection: >=2 guarded mutations around a yield point "
                   "(torn state), or manual acquire without try/finally")
    allowlist = ALLOW_KILL_SAFETY

    def _raw_findings(self) -> List[RawFinding]:
        return self._shared.kill


class CvDisciplineRule(_ConcurrencyRule):
    id = "cv-discipline"
    description = ("Condition misuse: wait outside a while-predicate loop "
                   "(spurious wakeups, ignored wait(timeout) return), or "
                   "wait/notify without holding the condition")
    allowlist = ALLOW_CV_DISCIPLINE

    def _raw_findings(self) -> List[RawFinding]:
        return self._shared.cv


def concurrency_rules() -> List[Rule]:
    """Fresh instances of the four rules sharing ONE analysis, so the
    whole-program walk runs once per engine run."""
    shared = ConcurrencyAnalysis()
    return [LockOrderRule(shared), BlockingUnderLockRule(shared),
            KillSafetyRule(shared), CvDisciplineRule(shared)]

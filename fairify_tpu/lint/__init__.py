"""``fairify_tpu.lint`` — the repo's rule-engine static-analysis framework.

A fast AST-only analysis (no jax import, no execution of the code under
analysis) exposed as ``fairify_tpu lint`` and ``scripts/lint.py`` and run
by tier-1 via ``tests/test_lint.py``.  See DESIGN.md §11 for the contract
and ``fairify_tpu/lint/rules.py`` for the nine-rule catalog.

Public surface::

    from fairify_tpu import lint
    result = lint.run_lint()          # LintResult over the whole repo
    rc = lint.main(["--format", "json"])   # the CLI entry

(The PR 6 migration shim ``scripts/lint_obs.py`` is gone; this engine is
the only lint entry point.)
"""
from fairify_tpu.lint.core import (  # noqa: F401
    BASELINE_REL,
    FileContext,
    Finding,
    LintResult,
    Rule,
    load_baseline,
    main,
    render_text,
    repo_root,
    run_lint,
)
from fairify_tpu.lint.rules import all_rules, legacy_rules  # noqa: F401

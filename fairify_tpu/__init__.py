"""fairify_tpu — a TPU-native individual-fairness verification framework.

A ground-up JAX/XLA re-design of the capabilities of the Fairify artifact
(ICSE 2023, reference at /root/reference): given a trained MLP classifier,
a tabular attribute domain, and a set of protected attributes, decide for
each box of a partitioned input domain whether a pair (x, x') exists that
agrees on all non-protected attributes, differs on a protected one, and is
classified differently (SAT), or prove no such pair exists (UNSAT).

Architectural stance (TPU-first, not a port):

* Every numeric stage of the reference — simulation forward passes
  (``utils/prune.py:168-222``), interval bound propagation
  (``utils/prune.py:105-164``), counterexample replay and accuracy parity
  (``utils/verif_utils.py:1040-1047``) — is a batched, `vmap`/`jit`-compiled
  XLA kernel over *static shapes*.  Pruned neurons are masks, never ragged
  deletes, so partitions × models × samples batch onto the MXU.
* The reference's decision procedure (Z3 SMT, ``src/GC/Verify-GC.py:145-214``)
  is replaced by a native complete verifier: batched CROWN/IBP bounds on a
  *pair network* drive an input-space branch-and-bound over the integer
  attribute lattice (complete because the lattice is finite), with a
  device-side counterexample attack for fast SAT certificates.  A gated Z3
  backend is retained for environments that have `z3-solver` installed.
* The partition sweep — the reference's outer loop
  (``src/GC/Verify-GC.py:106``) — shards over a `jax.sharding.Mesh`
  (ICI within a pod, DCN across hosts).
"""

__version__ = "0.1.0"

from fairify_tpu.models.mlp import MLP  # noqa: F401

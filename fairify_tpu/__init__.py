"""fairify_tpu — a TPU-native individual-fairness verification framework.

A ground-up JAX/XLA re-design of the capabilities of the Fairify artifact
(ICSE 2023, reference at /root/reference): given a trained MLP classifier,
a tabular attribute domain, and a set of protected attributes, decide for
each box of a partitioned input domain whether a pair (x, x') exists that
agrees on all non-protected attributes, differs on a protected one, and is
classified differently (SAT), or prove no such pair exists (UNSAT).

Architectural stance (TPU-first, not a port):

* Every numeric stage of the reference — simulation forward passes
  (``utils/prune.py:168-222``), interval bound propagation
  (``utils/prune.py:105-164``), counterexample replay and accuracy parity
  (``utils/verif_utils.py:1040-1047``) — is a batched, `vmap`/`jit`-compiled
  XLA kernel over *static shapes*.  Pruned neurons are masks, never ragged
  deletes, so partitions × models × samples batch onto the MXU.
* The reference's decision procedure (Z3 SMT, ``src/GC/Verify-GC.py:145-214``)
  is replaced by a native complete verifier: batched CROWN/IBP bounds on a
  *pair network* drive an input-space branch-and-bound over the integer
  attribute lattice (complete because the lattice is finite), with a
  device-side counterexample attack for fast SAT certificates.  A gated Z3
  backend is retained for environments that have `z3-solver` installed.
* The partition sweep — the reference's outer loop
  (``src/GC/Verify-GC.py:106``) — shards over a `jax.sharding.Mesh`
  (ICI within a pod, DCN across hosts).
"""

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy MLP re-export (PEP 562): importing the package must stay cheap
    # for jax-free subprocesses — the SMT worker (fairify_tpu.smt.worker)
    # imports fairify_tpu.smt.* hundreds of times per sweep across
    # respawns, and models.mlp drags the whole jax stack in (~2 s + a
    # large address-space map that would collide with the worker's
    # RLIMIT_AS cap).
    if name == "MLP":
        from fairify_tpu.models.mlp import MLP

        return MLP
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

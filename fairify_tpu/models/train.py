"""From-scratch MLP training for synthetic zoo models.

The reference generates several models at runtime rather than shipping them:
GC-6..8 come from synthetic-data comparison pipelines
(``src/GC/Verify-GC-experiment.py:88-107``) and AC-13..16 from the repair
pipelines (``src/AC/detect_bias.py:408``).  This trainer produces
equivalently-shaped ReLU/sigmoid MLPs with optax so the full model-family
surface exists without TensorFlow.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fairify_tpu.models.mlp import MLP, from_numpy
from fairify_tpu.analysis.repair import bce_loss


def init_mlp(sizes: Sequence[int], seed: int = 0, scale: float = 0.1) -> MLP:
    rng = np.random.default_rng(seed)
    ws, bs = [], []
    for i in range(len(sizes) - 1):
        # He-style fan-in scaling, standard for ReLU stacks.
        std = scale * np.sqrt(2.0 / sizes[i])
        ws.append(rng.normal(scale=std, size=(sizes[i], sizes[i + 1])).astype(np.float32))
        bs.append(np.zeros(sizes[i + 1], dtype=np.float32))
    return from_numpy(ws, bs)


def train_mlp(
    X,
    y,
    hidden: Sequence[int],
    epochs: int = 20,
    lr: float = 1e-3,
    batch_size: int = 128,
    seed: int = 0,
    standardize: bool = True,
) -> MLP:
    """Train a binary classifier MLP (ReLU hidden, logit output).

    With ``standardize`` the optimizer sees zero-mean/unit-variance
    features (raw integer attributes span 0..10^5 across these datasets,
    which otherwise collapses training to the majority class); the affine
    transform is folded exactly into the first layer afterwards, so the
    returned network still consumes the raw integer lattice that
    verification domains are defined over.
    """
    X = np.asarray(X, dtype=np.float32)
    y = np.asarray(y, dtype=np.float32)
    if standardize:
        mu = X.mean(axis=0)
        sd = X.std(axis=0)
        sd[sd == 0] = 1.0
        X = (X - mu) / sd
    sizes = [X.shape[1], *hidden, 1]
    net = init_mlp(sizes, seed)
    params = (net.weights, net.biases)
    opt = optax.adam(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            return bce_loss(MLP(p[0], p[1], net.masks), xb, yb)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(seed)
    n = X.shape[0]
    Xj, yj = jnp.asarray(X), jnp.asarray(y)
    for _ in range(epochs):
        order = rng.permutation(n)
        for s in range(0, n - batch_size + 1, batch_size):
            idx = order[s : s + batch_size]
            params, opt_state, _ = step(params, opt_state, Xj[idx], yj[idx])
    ws, bs = list(params[0]), list(params[1])
    if standardize:
        # fold x -> (x-mu)/sd into layer 0: W' = W/sd, b' = b - (mu/sd)@W
        w0 = np.asarray(ws[0]) / sd[:, None]
        b0 = np.asarray(bs[0]) - (mu / sd) @ np.asarray(ws[0])
        ws[0] = jnp.asarray(w0.astype(np.float32))
        bs[0] = jnp.asarray(b0.astype(np.float32))
    return MLP(tuple(ws), tuple(bs), net.masks)

"""Registry of the benchmark model zoo.

The reference ships 62 pretrained ``.h5`` MLPs under ``models/{adult,german,
bank,compass,default}`` (SURVEY.md §2.4); drivers iterate a directory listing
(``src/GC/Verify-GC.py:78-80``).  The registry resolves the same families from
a configurable root so the suite runs against the read-only reference assets
or a local copy.
"""
from __future__ import annotations

import os
import re
from pathlib import Path

from fairify_tpu.models.ingest import load_keras_h5

DEFAULT_ROOT = os.environ.get("FAIRIFY_TPU_MODEL_ROOT", "/root/reference/models")

# dataset key -> (subdirectory, model-name prefix)
FAMILIES = {
    "adult": ("adult", "AC"),
    "german": ("german", "GC"),
    "bank": ("bank", "BM"),
    "compass": ("compass", "CP"),
    "compass12": ("compass", "CP"),
    "default": ("default", "DF"),
}


def _natural_key(name: str):
    return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", name)]


def model_paths(dataset: str, root=None) -> list:
    """Sorted ``.h5`` paths for a dataset family (AC-1, AC-2, ... order)."""
    sub, _ = FAMILIES[dataset]
    root = Path(root or DEFAULT_ROOT)
    d = root / sub
    if not d.is_dir():
        return []
    return sorted(d.glob("*.h5"), key=lambda p: _natural_key(p.stem))


def load(dataset: str, name: str, root=None):
    """Load one zoo model by name, e.g. ``load('german', 'GC-1')``."""
    sub, _ = FAMILIES[dataset]
    root = Path(root or DEFAULT_ROOT)
    return load_keras_h5(root / sub / f"{name}.h5")


def load_family(dataset: str, root=None) -> dict:
    return {p.stem: load_keras_h5(p) for p in model_paths(dataset, root)}


def load_matching(dataset: str, n_attrs: int, models=None, root=None):
    """Zoo models whose input width matches the verification domain.

    Returns ``(nets, skipped)``: ``nets`` maps name → net for every model
    with ``in_dim == n_attrs`` (optionally restricted to ``models``),
    ``skipped`` lists the mismatched names (e.g. the 12-input CP notebook
    models vs the 6-attribute domain).  Shared by the sweep driver and the
    metrics CLI so the selection rules cannot drift.
    """
    nets, skipped = {}, []
    for path in model_paths(dataset, root=root):
        if models is not None and path.stem not in models:
            continue
        net = load(dataset, path.stem, root=root)
        if net.in_dim != n_attrs:
            skipped.append(path.stem)
            continue
        nets[path.stem] = net
    return nets, skipped

"""Gradient-boosted shallow trees — the from-scratch strong tabular teacher.

The reference's ``experimentData/task3`` notebooks train MLP students
against labels predicted by TabPFN, a pretrained-transformer tabular
classifier.  TabPFN's checkpoint is unfetchable in this environment, so
the task3 analog needs a strong tabular teacher built from scratch
(VERDICT r3 #7).  Gradient boosting over depth-2 trees with Newton leaf
steps is the classical strong baseline on exactly these small tabular
datasets (adult/bank-class); depth 2 matters — depth-1 stumps yield an
additive model that cannot represent feature interactions (XOR-class
structure), which is what separates a strong teacher from logistic
regression.

Training is host-side numpy by design: teachers label datasets once at
experiment setup; the TPU path of this framework is verification of the
*students*.  The split search is fully vectorized per feature (prefix-sum
gain scan over the sorted column), so fitting 300 rounds on the adult
train split takes seconds.

Semantics: binary logistic loss.  Per round, a depth-``max_depth`` tree is
grown by exact greedy split search on the gradient/hessian statistics
(g = y − p, h = p(1−p)); leaf values are shrunken Newton steps
lr·Σg/(Σh+λ).  Prediction is the signed logit margin; ``predict``
thresholds at 0.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np


@dataclass
class _Node:
    # Internal node: feature/threshold set, value unset.  Leaf: value set.
    feature: int = -1
    threshold: float = 0.0
    left: Optional["_Node"] = None
    right: Optional["_Node"] = None
    value: float = 0.0

    @property
    def is_leaf(self) -> bool:
        return self.feature < 0


@dataclass
class GradientBoostedTrees:
    n_rounds: int = 300
    learning_rate: float = 0.1
    max_depth: int = 2
    reg_lambda: float = 1.0
    min_child_weight: float = 1.0
    bias: float = 0.0
    trees: List[_Node] = field(default_factory=list)

    def fit(self, X: np.ndarray, y: np.ndarray) -> "GradientBoostedTrees":
        X = np.asarray(X, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64).ravel()
        n, _ = X.shape
        p0 = float(np.clip(y.mean(), 1e-6, 1 - 1e-6))
        self.bias = float(np.log(p0 / (1.0 - p0)))
        F = np.full(n, self.bias)
        self.trees = []
        for _ in range(self.n_rounds):
            p = 1.0 / (1.0 + np.exp(-F))
            g = y - p
            h = np.maximum(p * (1.0 - p), 1e-12)
            root = self._grow(X, g, h, np.arange(n), self.max_depth)
            if root.is_leaf:
                # No split with positive gain anywhere: boosting has
                # converged — appending further (constant-leaf) trees only
                # bloats the model.  The leaf's Newton value is absorbed
                # into nothing; stop cleanly.
                break
            self.trees.append(root)
            F = F + self._tree_margin(root, X)
        return self

    # -- tree growing ------------------------------------------------------

    def _leaf(self, g, h, idx) -> _Node:
        val = self.learning_rate * g[idx].sum() / (h[idx].sum() + self.reg_lambda)
        return _Node(value=float(val))

    def _grow(self, X, g, h, idx, depth) -> _Node:
        if depth == 0 or idx.size < 2 * int(self.min_child_weight):
            return self._leaf(g, h, idx)
        split = self._best_split(X, g, h, idx)
        if split is None:
            return self._leaf(g, h, idx)
        j, thr = split
        go_left = X[idx, j] <= thr
        node = _Node(feature=j, threshold=thr)
        node.left = self._grow(X, g, h, idx[go_left], depth - 1)
        node.right = self._grow(X, g, h, idx[~go_left], depth - 1)
        return node

    def _best_split(self, X, g, h, idx):
        """Exact greedy (feature, threshold) maximizing the gain
        gl²/(hl+λ) + gr²/(hr+λ) − (G²/(H+λ)); vectorized prefix-sum scan
        over each sorted column restricted to ``idx``."""
        G, H = g[idx].sum(), h[idx].sum()
        lam = self.reg_lambda
        base = (G * G) / (H + lam)
        best_gain, best = 1e-12, None
        for j in range(X.shape[1]):
            xs_all = X[idx, j]
            o = np.argsort(xs_all, kind="stable")
            xs = xs_all[o]
            gl = np.cumsum(g[idx][o])[:-1]
            hl = np.cumsum(h[idx][o])[:-1]
            distinct = xs[1:] != xs[:-1]
            hr = H - hl
            ok = distinct & (hl >= self.min_child_weight) \
                & (hr >= self.min_child_weight)
            if not ok.any():
                continue
            gain = gl * gl / (hl + lam) + (G - gl) ** 2 / (hr + lam) - base
            gain = np.where(ok, gain, -np.inf)
            k = int(gain.argmax())
            if gain[k] > best_gain:
                best_gain = float(gain[k])
                best = (j, float(0.5 * (xs[k] + xs[k + 1])))
        return best

    # -- inference ---------------------------------------------------------

    def _tree_margin(self, node: _Node, X: np.ndarray) -> np.ndarray:
        if node.is_leaf:
            return np.full(X.shape[0], node.value)
        go_left = X[:, node.feature] <= node.threshold
        out = np.empty(X.shape[0])
        out[go_left] = self._tree_margin(node.left, X[go_left])
        out[~go_left] = self._tree_margin(node.right, X[~go_left])
        return out

    def decision_function(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, dtype=np.float64)
        F = np.full(X.shape[0], self.bias)
        for t in self.trees:
            F += self._tree_margin(t, X)
        return F

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        return 1.0 / (1.0 + np.exp(-self.decision_function(X)))

    def predict(self, X: np.ndarray) -> np.ndarray:
        return (self.decision_function(X) > 0.0).astype(np.int64)


def feature_importances(model: GradientBoostedTrees, d: int) -> np.ndarray:
    """Split-count importances (diagnostic parity with sklearn teachers)."""
    counts = np.zeros(d, dtype=np.float64)

    def walk(node):
        if node is None or node.is_leaf:
            return
        counts[node.feature] += 1.0
        walk(node.left)
        walk(node.right)

    for t in model.trees:
        walk(t)
    total = counts.sum()
    return counts / total if total else counts

"""Keras HDF5 → weight pytree ingestion, with no TensorFlow dependency.

The reference loads every ``.h5`` through ``tensorflow.keras.load_model`` and
strips weights layer by layer (``utils/verif_utils.py:486-499``,
``src/GC/Verify-GC.py:92-96``).  Here the HDF5 file is parsed directly with
``h5py``: the ``model_config`` attribute gives the layer order and activations,
``model_weights/<name>/<name>/{kernel,bias}:0`` the parameters.  This avoids
dragging the TF runtime into the verification path and works for every model
in the reference zoo (all are Sequential stacks of Dense layers).
"""
from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from fairify_tpu.models.mlp import MLP, from_numpy


class IngestError(ValueError):
    pass


def _layer_configs(cfg: dict) -> list:
    layers = cfg["config"]["layers"]
    out = []
    for layer in layers:
        cls = layer["class_name"]
        if cls == "InputLayer":
            continue
        if cls != "Dense":
            raise IngestError(f"unsupported layer class {cls!r}")
        out.append(layer["config"])
    return out


def _weight_arrays(h5file, layer_name: str):
    import h5py  # local import keeps module importable without h5py

    grp = h5file["model_weights"][layer_name]
    # Keras nests one more group level named after the layer.
    while isinstance(grp, h5py.Group) and "kernel:0" not in grp:
        inner = [k for k in grp.keys()]
        if len(inner) != 1:
            raise IngestError(f"ambiguous weight group for {layer_name}: {inner}")
        grp = grp[inner[0]]
    return np.array(grp["kernel:0"]), np.array(grp["bias:0"])


def load_keras_h5(path) -> MLP:
    """Load a Keras Sequential/Functional Dense-only ``.h5`` model as an MLP.

    Validates the reference architecture contract: ReLU hidden layers and a
    single sigmoid (or linear) output unit — the class of networks Fairify
    verifies (``README.md``; every zoo model satisfies it).  The returned MLP
    computes the pre-sigmoid logit, as the reference's ``net`` does.
    """
    import h5py

    path = Path(path)
    with h5py.File(path, "r") as f:
        raw = f.attrs.get("model_config")
        if raw is None:
            raise IngestError(f"{path}: no model_config attribute")
        if isinstance(raw, bytes):
            raw = raw.decode("utf-8")
        cfg = json.loads(raw)
        layer_cfgs = _layer_configs(cfg)
        if not layer_cfgs:
            raise IngestError(f"{path}: no Dense layers")
        weights, biases = [], []
        for lc in layer_cfgs:
            k, b = _weight_arrays(f, lc["name"])
            weights.append(k.astype(np.float32))
            biases.append(b.astype(np.float32))

    for i, lc in enumerate(layer_cfgs[:-1]):
        if lc.get("activation") != "relu":
            raise IngestError(
                f"{path}: hidden layer {i} activation {lc.get('activation')!r}, expected relu"
            )
    last = layer_cfgs[-1]
    if last.get("activation") not in ("sigmoid", "linear"):
        raise IngestError(f"{path}: output activation {last.get('activation')!r}")
    if weights[-1].shape[1] != 1:
        raise IngestError(f"{path}: output width {weights[-1].shape[1]}, expected 1")

    for i in range(len(weights) - 1):
        if weights[i].shape[1] != weights[i + 1].shape[0]:
            raise IngestError(f"{path}: inconsistent layer shapes at {i}")

    return from_numpy(weights, biases)

"""Synthetic tabular-data generators (the reference's task1 analog).

The reference's ``experimentData/task1`` notebooks synthesize German-credit
rows with CTGAN and (distil)GPT-2, train new models (GC-6..8) on the
synthetic rows, and compare their verification outcomes against the
real-data models (``src/GC/Verify-GC-experiment.py:88-107``).  This module
provides the same capability with from-scratch generators (no pretrained
checkpoints, no external fetch), both over the integer attribute lattice of
a :class:`~fairify_tpu.data.domains.DomainSpec`:

* :class:`GaussianCopula` — empirical per-column marginals coupled by a
  latent Gaussian correlation (the CTGAN-lite analog; closed-form fit).
* :class:`ARColumnModel` — an autoregressive categorical model over the
  column sequence (the LM analog): a shared MLP trunk over causally-masked
  one-hot prefixes with one softmax head per column, trained with optax and
  sampled column-by-column on device.

Both generators model the label column jointly with the features, so
sampled rows arrive fully labelled — matching how the reference's
generators emit complete rows.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fairify_tpu.utils import prng


# ---------------------------------------------------------------------------
# Gaussian copula
# ---------------------------------------------------------------------------

def _norm_ppf(u: np.ndarray) -> np.ndarray:
    """Inverse standard-normal CDF (Acklam's rational approximation).

    Avoids a scipy dependency in the sampling path; max abs error ~1e-9,
    far below the integer-lattice quantization of the output.
    """
    u = np.clip(u, 1e-12, 1 - 1e-12)
    a = [-3.969683028665376e+01, 2.209460984245205e+02, -2.759285104469687e+02,
         1.383577518672690e+02, -3.066479806614716e+01, 2.506628277459239e+00]
    b = [-5.447609879822406e+01, 1.615858368580409e+02, -1.556989798598866e+02,
         6.680131188771972e+01, -1.328068155288572e+01]
    c = [-7.784894002430293e-03, -3.223964580411365e-01, -2.400758277161838e+00,
         -2.549732539343734e+00, 4.374664141464968e+00, 2.938163982698783e+00]
    d = [7.784695709041462e-03, 3.224671290700398e-01, 2.445134137142996e+00,
         3.754408661907416e+00]
    lo, hi = 0.02425, 1 - 0.02425
    out = np.empty_like(u)
    m = u < lo
    if m.any():
        q = np.sqrt(-2 * np.log(u[m]))
        out[m] = (((((c[0] * q + c[1]) * q + c[2]) * q + c[3]) * q + c[4]) * q + c[5]) / \
                 ((((d[0] * q + d[1]) * q + d[2]) * q + d[3]) * q + 1)
    m = (u >= lo) & (u <= hi)
    if m.any():
        q = u[m] - 0.5
        r = q * q
        out[m] = (((((a[0] * r + a[1]) * r + a[2]) * r + a[3]) * r + a[4]) * r + a[5]) * q / \
                 (((((b[0] * r + b[1]) * r + b[2]) * r + b[3]) * r + b[4]) * r + 1)
    m = u > hi
    if m.any():
        q = np.sqrt(-2 * np.log(1 - u[m]))
        out[m] = -(((((c[0] * q + c[1]) * q + c[2]) * q + c[3]) * q + c[4]) * q + c[5]) / \
                 ((((d[0] * q + d[1]) * q + d[2]) * q + d[3]) * q + 1)
    return out


def _norm_cdf(z: np.ndarray) -> np.ndarray:
    from math import sqrt

    try:
        from scipy.special import erf
    except ImportError:  # pragma: no cover - scipy is a sklearn dependency here
        import math

        erf = np.vectorize(math.erf)
    return 0.5 * (1.0 + erf(z / sqrt(2.0)))


@dataclass
class GaussianCopula:
    """Empirical-marginal Gaussian copula over integer columns.

    ``values[j]``/``cum[j]`` give column *j*'s observed support and its
    cumulative probabilities; ``chol`` is the Cholesky factor of the
    normal-scores correlation matrix.
    """

    values: List[np.ndarray]
    cum: List[np.ndarray]
    chol: np.ndarray

    @staticmethod
    def fit(X: np.ndarray) -> "GaussianCopula":
        X = np.asarray(X)
        n, d = X.shape
        values, cum, scores = [], [], np.empty((n, d))
        for j in range(d):
            col = X[:, j]
            vals, counts = np.unique(col, return_counts=True)
            p = counts / n
            cj = np.cumsum(p)
            values.append(vals.astype(np.int64))
            cum.append(cj)
            # mid-CDF normal scores keep ties well-defined on discrete data
            mid = cj - p / 2.0
            lookup = {v: mid[i] for i, v in enumerate(vals)}
            scores[:, j] = _norm_ppf(np.array([lookup[v] for v in col]))
        corr = np.corrcoef(scores, rowvar=False)
        corr = np.atleast_2d(corr)
        # jitter for numerical PD-ness on near-degenerate columns
        corr = corr + 1e-6 * np.eye(d)
        np.nan_to_num(corr, copy=False, nan=0.0)
        np.fill_diagonal(corr, 1.0 + 1e-6)
        chol = np.linalg.cholesky(corr)
        return GaussianCopula(values, cum, chol)

    def sample(self, n: int, seed: int = 0) -> np.ndarray:
        d = self.chol.shape[0]
        rng = np.random.default_rng(seed)
        z = rng.standard_normal((n, d)) @ self.chol.T
        u = _norm_cdf(z)
        out = np.empty((n, d), dtype=np.int64)
        for j in range(d):
            idx = np.searchsorted(self.cum[j], u[:, j], side="left")
            idx = np.clip(idx, 0, len(self.values[j]) - 1)
            out[:, j] = self.values[j][idx]
        return out


# ---------------------------------------------------------------------------
# Column quantizer: bounded-cardinality view of wide integer columns
# ---------------------------------------------------------------------------

@dataclass
class ColumnQuantizer:
    """Maps wide integer columns onto ≤``max_card`` frequency bins.

    The AR model below is categorical per column; German's ``credit_amount``
    spans 0..20000, which would make its one-hot input 20k wide and its
    softmax unlearnable from ~1k rows.  Narrow columns pass through
    unchanged; wide ones are binned at empirical quantile edges, and
    decoding draws uniformly among the *observed* values of the bin — so
    decoded rows always stay on the dataset's support (like the copula).
    """

    bins: List[List[np.ndarray]]   # bins[j][k] = observed values of bin k
    edges: List[np.ndarray]        # bin upper-bound edges for encode()

    @staticmethod
    def fit(X: np.ndarray, max_card: int = 64) -> "ColumnQuantizer":
        X = np.asarray(X)
        bins, edges = [], []
        for j in range(X.shape[1]):
            vals = np.unique(X[:, j])
            if len(vals) <= max_card:
                bins.append([np.array([v]) for v in vals])
                edges.append(vals.astype(np.float64))
            else:
                qs = np.quantile(X[:, j], np.linspace(0, 1, max_card + 1)[1:])
                ub = np.unique(qs)                      # bin upper bounds
                idx = np.searchsorted(ub, vals, side="left")
                kept = np.unique(idx)                   # drop empty bins
                bins.append([vals[idx == k] for k in kept])
                edges.append(ub[kept].astype(np.float64))
        return ColumnQuantizer(bins, edges)

    @property
    def card(self) -> np.ndarray:
        return np.array([len(b) for b in self.bins], dtype=np.int64)

    def encode(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X)
        out = np.empty_like(X, dtype=np.int64)
        for j, ub in enumerate(self.edges):
            out[:, j] = np.clip(np.searchsorted(ub, X[:, j], side="left"),
                                0, len(self.bins[j]) - 1)
        return out

    def decode(self, B: np.ndarray, seed: int = 0) -> np.ndarray:
        rng = np.random.default_rng(seed)
        B = np.asarray(B)
        out = np.empty_like(B, dtype=np.int64)
        for j, col_bins in enumerate(self.bins):
            for k, vals in enumerate(col_bins):
                m = B[:, j] == k
                if m.any():
                    out[m, j] = vals[rng.integers(0, len(vals), size=int(m.sum()))]
        return out


# ---------------------------------------------------------------------------
# Autoregressive column model (JAX)
# ---------------------------------------------------------------------------

@dataclass
class ARColumnModel:
    """p(x) = prod_j p(x_j | x_<j>) over integer columns, MLP trunk + heads.

    One-hot prefix encoding with causal masking; shared two-layer trunk;
    per-column heads stored as one padded ``(d, H, Kmax)`` tensor so both
    training and sampling are single fused einsums on device.
    """

    lo: np.ndarray            # (d,) column minima
    card: np.ndarray          # (d,) column cardinalities
    offsets: np.ndarray       # (d,) one-hot block offsets
    params: dict              # trunk/head weights (jnp arrays)

    # -- construction -------------------------------------------------------
    @staticmethod
    def init(lo: Sequence[int], hi: Sequence[int], hidden: int = 64, seed: int = 0) -> "ARColumnModel":
        lo = np.asarray(lo, dtype=np.int64)
        hi = np.asarray(hi, dtype=np.int64)
        card = (hi - lo + 1).astype(np.int64)
        offsets = np.concatenate([[0], np.cumsum(card)[:-1]])
        D = int(card.sum())
        d = len(card)
        kmax = int(card.max())
        rng = np.random.default_rng(seed)

        def lin(i, o):
            return rng.normal(scale=np.sqrt(2.0 / i), size=(i, o)).astype(np.float32)

        params = {
            "w1": jnp.asarray(lin(D, hidden)), "b1": jnp.zeros(hidden, jnp.float32),
            "w2": jnp.asarray(lin(hidden, hidden)), "b2": jnp.zeros(hidden, jnp.float32),
            "wh": jnp.asarray(rng.normal(scale=0.05, size=(d, hidden, kmax)).astype(np.float32)),
            "bh": jnp.zeros((d, kmax), jnp.float32),
        }
        return ARColumnModel(lo, card, offsets, params)

    # -- shared pieces -------------------------------------------------------
    def _consts(self):
        d = len(self.card)
        D = int(self.card.sum())
        kmax = int(self.card.max())
        col_of = np.repeat(np.arange(d), self.card)          # (D,) one-hot slot -> column
        class_mask = (np.arange(kmax)[None, :] < self.card[:, None])  # (d, kmax)
        return d, D, kmax, jnp.asarray(col_of), jnp.asarray(class_mask)

    def _onehot(self, X: np.ndarray) -> np.ndarray:
        """(n, d) ints -> (n, D) concatenated one-hots."""
        n, d = X.shape
        idx = (X - self.lo[None, :]) + self.offsets[None, :]
        out = np.zeros((n, int(self.card.sum())), dtype=np.float32)
        out[np.arange(n)[:, None], idx] = 1.0
        return out

    # -- training ------------------------------------------------------------
    def fit(self, X: np.ndarray, epochs: int = 300, lr: float = 3e-3,
            batch_size: int = 256, seed: int = 0) -> List[float]:
        X = np.asarray(X, dtype=np.int64)
        X = np.clip(X, self.lo[None, :], (self.lo + self.card - 1)[None, :])
        d, D, kmax, col_of, class_mask = self._consts()
        oh = self._onehot(X)                                  # (n, D)
        tgt = (X - self.lo[None, :]).astype(np.int32)         # (n, d)
        # causal[j, i] keeps one-hot slot i only if its column precedes j
        causal = (col_of[None, :] < jnp.arange(d)[:, None]).astype(jnp.float32)
        neg = jnp.where(class_mask, 0.0, -1e30)               # (d, kmax)

        def loss_fn(params, xb, yb):
            # xb: (B, D) one-hot rows; prefixes for all d targets at once
            pref = xb[:, None, :] * causal[None, :, :]        # (B, d, D)
            h = jax.nn.relu(jnp.einsum("bdi,ih->bdh", pref, params["w1"]) + params["b1"])
            h = jax.nn.relu(jnp.einsum("bdh,hk->bdk", h, params["w2"]) + params["b2"])
            logits = jnp.einsum("bdh,dhk->bdk", h, params["wh"]) + params["bh"] + neg
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(logp, yb[:, :, None], axis=-1)[..., 0]
            return -ll.mean()

        opt = optax.adam(lr)
        params = self.params
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, xb, yb):
            l, g = jax.value_and_grad(loss_fn)(params, xb, yb)
            upd, opt_state = opt.update(g, opt_state)
            return optax.apply_updates(params, upd), opt_state, l

        n = len(X)
        rng = np.random.default_rng(seed)
        hist = []
        for ep in range(epochs):
            order = rng.permutation(n)
            tot = 0.0
            for s in range(0, n, batch_size):
                sel = order[s:s + batch_size]
                params, opt_state, l = step(params, opt_state,
                                            jnp.asarray(oh[sel]), jnp.asarray(tgt[sel]))
                tot += float(l) * len(sel)
            hist.append(tot / n)
        self.params = params
        return hist

    # -- sampling ------------------------------------------------------------
    def sample(self, n: int, seed: int = 0) -> np.ndarray:
        d, D, kmax, col_of, class_mask = self._consts()
        neg = jnp.where(class_mask, 0.0, -1e30)
        offsets = jnp.asarray(self.offsets)
        params = self.params

        def trunk(x):  # (n, D) -> (n, H)
            h = jax.nn.relu(x @ params["w1"] + params["b1"])
            return jax.nn.relu(h @ params["w2"] + params["b2"])

        @jax.jit
        def draw(key):
            x = jnp.zeros((n, D), jnp.float32)
            cols = []
            for j in range(d):  # static unroll over columns
                h = trunk(x)
                logits = h @ params["wh"][j] + params["bh"][j] + neg[j]
                key, sub = jax.random.split(key)
                cj = jax.random.categorical(sub, logits, axis=-1)  # (n,)
                cols.append(cj)
                x = x.at[jnp.arange(n), offsets[j] + cj].set(1.0)
            return jnp.stack(cols, axis=1)

        cls = np.asarray(draw(prng.run_key(seed)))
        return cls.astype(np.int64) + self.lo[None, :]


# ---------------------------------------------------------------------------
# Bootstrap baseline
# ---------------------------------------------------------------------------

def bootstrap_rows(X: np.ndarray, n: int, seed: int = 0) -> np.ndarray:
    """Resample-with-replacement baseline generator (task1's third arm)."""
    rng = np.random.default_rng(seed)
    X = np.asarray(X)
    return X[rng.integers(0, len(X), size=n)]


GENERATORS = ("copula", "ar", "bootstrap")


def synthesize(kind: str, X: np.ndarray, lo, hi, n: int, seed: int = 0,
               ar_epochs: int = 200, ar_hidden: int = 64) -> np.ndarray:
    """Fit generator ``kind`` on labelled rows ``X`` and sample ``n`` rows.

    Rows are clipped to the ``[lo, hi]`` domain lattice first, so every
    generator's output support stays inside the verification domain even
    when the raw dataset carries out-of-spec values.
    """
    lo = np.asarray(lo, dtype=np.int64)
    hi = np.asarray(hi, dtype=np.int64)
    X = np.clip(np.asarray(X, dtype=np.int64), lo[None, :], hi[None, :])
    if kind == "copula":
        return GaussianCopula.fit(X).sample(n, seed=seed)
    if kind == "ar":
        # bounded-cardinality view keeps the one-hot width ~d*64 even when
        # a column spans 0..20000 (German credit_amount)
        q = ColumnQuantizer.fit(X)
        B = q.encode(X)
        card = q.card
        m = ARColumnModel.init(np.zeros_like(card), card - 1,
                               hidden=ar_hidden, seed=seed)
        m.fit(B, epochs=ar_epochs, seed=seed)
        return q.decode(m.sample(n, seed=seed + 1), seed=seed + 2)
    if kind == "bootstrap":
        return bootstrap_rows(X, n, seed=seed)
    raise ValueError(f"unknown generator {kind!r}; options: {GENERATORS}")

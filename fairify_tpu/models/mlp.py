"""Static-shape masked MLP — the single network representation of the framework.

The reference keeps 53 hand-written per-model ``utils/*-Model-Functions.py``
files, each duplicating ``net``/``layer_net``/``z3_net`` for one architecture
(e.g. ``utils/GC-1-Model-Functions.py:16-44``).  Here one depth-generic pytree
covers every model; the per-model symbolic encoders are unnecessary because
bounds and decisions are computed from the same weight pytree.

Pruning is represented as per-layer *alive masks* instead of the reference's
``np.delete`` excision (``utils/prune.py:950-977``): a pruned (provably dead)
hidden neuron never activates, so zeroing its post-activation is numerically
identical to removing it, and keeps all shapes static for XLA.  Dense excision
for reporting/compression lives in :mod:`fairify_tpu.ops.masks`.
"""
from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fairify_tpu.utils.num import matmul


class MLP(NamedTuple):
    """A fully-connected ReLU network with a linear final layer.

    ``weights[i]`` has shape ``(in_i, out_i)`` (Keras kernel layout),
    ``biases[i]`` shape ``(out_i,)``, ``masks[i]`` shape ``(out_i,)`` with
    1.0 = alive, 0.0 = pruned.  The final layer's mask is all-ones (the
    reference never prunes the output layer, ``utils/prune.py:235-236``).
    """

    weights: tuple
    biases: tuple
    masks: tuple

    @property
    def depth(self) -> int:
        return len(self.weights)

    @property
    def layer_sizes(self) -> tuple:
        return tuple(int(w.shape[1]) for w in self.weights)

    @property
    def in_dim(self) -> int:
        return int(self.weights[0].shape[0])

    def with_masks(self, masks: Sequence[jax.Array]) -> "MLP":
        return MLP(self.weights, self.biases, tuple(masks))

    def unmasked(self) -> "MLP":
        return MLP(
            self.weights,
            self.biases,
            tuple(jnp.ones_like(b) for b in self.biases),
        )


def from_numpy(weights, biases, masks=None) -> MLP:
    """Build an :class:`MLP` from host weight/bias lists (float32)."""
    ws = tuple(jnp.asarray(np.asarray(w), dtype=jnp.float32) for w in weights)
    bs = tuple(jnp.asarray(np.asarray(b), dtype=jnp.float32) for b in biases)
    if masks is None:
        ms = tuple(jnp.ones_like(b) for b in bs)
    else:
        ms = tuple(jnp.asarray(np.asarray(m), dtype=jnp.float32) for m in masks)
    return MLP(ws, bs, ms)


def forward(params: MLP, x: jax.Array) -> jax.Array:
    """Logit of the network for a single input or a batch.

    Matches the reference's ``net`` (``utils/GC-1-Model-Functions.py:25-30``):
    ReLU hidden layers, raw logit output (no sigmoid).  ``x`` may be ``(d,)``
    or ``(..., d)``; the output drops the size-1 logit axis.
    """
    h = x
    n = len(params.weights)
    for i, (w, b, m) in enumerate(zip(params.weights, params.biases, params.masks)):
        z = matmul(h, w) + b
        h = z if i == n - 1 else jax.nn.relu(z) * m
    return jnp.squeeze(h, axis=-1)


def layer_outputs(params: MLP, x: jax.Array) -> list:
    """Post-activation outputs of every layer (final layer linear).

    Mirrors the reference's ``layer_net`` (``utils/GC-1-Model-Functions.py:16-23``)
    which drives dead-neuron candidate counting (``utils/prune.py:168-192``).
    """
    outs = []
    h = x
    n = len(params.weights)
    for i, (w, b, m) in enumerate(zip(params.weights, params.biases, params.masks)):
        z = matmul(h, w) + b
        h = z if i == n - 1 else jax.nn.relu(z) * m
        outs.append(h)
    return outs


def preactivations(params: MLP, x: jax.Array) -> list:
    """Pre-activation (weighted-sum) values of every layer."""
    outs = []
    h = x
    n = len(params.weights)
    for i, (w, b, m) in enumerate(zip(params.weights, params.biases, params.masks)):
        z = matmul(h, w) + b
        outs.append(z)
        h = z if i == n - 1 else jax.nn.relu(z) * m
    return outs


def forward_np(weights, biases, x: np.ndarray, dead=None) -> np.ndarray:
    """Host-side float64 logit replay (no device dispatch).

    Per-partition bookkeeping — counterexample replay (C-check/V-accurate,
    ``src/GC/Verify-GC.py:225-250``) and heuristic-retry parity — runs on a
    handful of points per partition; a device round-trip per call costs ~200ms
    of dispatch for a microsecond of math, so these paths stay in numpy.
    ``dead`` is an optional list of per-hidden-layer dead masks (1 = dead).
    """
    h = np.asarray(x, dtype=np.float64)
    n = len(weights)
    for i, (w, b) in enumerate(zip(weights, biases)):
        z = h @ np.asarray(w, dtype=np.float64) + np.asarray(b, dtype=np.float64)
        if i < n - 1:
            z = np.maximum(z, 0.0)
            if dead is not None:
                z = z * (1.0 - np.asarray(dead[i], dtype=np.float64))
        h = z
    return h[..., 0]


def predict_np(weights, biases, x: np.ndarray, dead=None) -> np.ndarray:
    """Host-side class decision (logit sign test), matching :func:`predict`."""
    return forward_np(weights, biases, x, dead=dead) > 0.0


def prediction_mismatch(weights, biases, X: np.ndarray, dead=None) -> np.ndarray:
    """Indices where the dead-masked (pruned) net disagrees with the original.

    The debugging helper ``y_pred_mismatch`` (``utils/verif_utils.py:1049-1063``)
    as one batched comparison instead of a per-sample print loop.
    """
    orig = predict_np(weights, biases, X)
    pruned = predict_np(weights, biases, X, dead=dead)
    return np.where(orig != pruned)[0]


def predict(params: MLP, x: jax.Array) -> jax.Array:
    """Boolean class decision: sigmoid(logit) > 0.5, i.e. logit > 0.

    The reference thresholds the sigmoid at 0.5 (``utils/verif_utils.py:1040-1047``);
    on logits that is exactly a sign test, which is also how the fairness
    property is phrased on logits (``src/GC/Verify-GC.py:154``).
    """
    return forward(params, x) > 0.0


def excise(params: MLP) -> MLP:
    """Materialize masks as a dense smaller network (host-side only).

    The result is numerically identical to ``forward`` on the masked network;
    used for reporting and for feeding an external SMT backend the same small
    matrices the reference produces with ``prune_neurons`` (``utils/prune.py:950-977``).
    """
    ws = [np.asarray(w) for w in params.weights]
    bs = [np.asarray(b) for b in params.biases]
    ms = [np.asarray(m) for m in params.masks]
    n = len(ws)
    for i in range(n):
        keep = ms[i] > 0.5
        ws[i] = ws[i][:, keep]
        bs[i] = bs[i][keep]
        if i + 1 < n:
            ws[i + 1] = ws[i + 1][keep, :]
    return from_numpy(ws, bs)


def local_affine_np(weights, biases, x):
    """Exact local affine form of the logit at ``x``: ``(f(x), df/dx)`` in f64.

    A ReLU MLP is affine within the activation region of ``x``, so the
    gradient is the product of the weight matrices masked by the active
    units — exact (up to f64 rounding), no autodiff or device dispatch.
    Used by the flip-slab search (``verify.engine.slab_search``).
    """
    h = np.asarray(x, dtype=np.float64)
    n = len(weights)
    masks = []
    f = 0.0
    for i, (w, b) in enumerate(zip(weights, biases)):
        z = h @ np.asarray(w, dtype=np.float64) + np.asarray(b, dtype=np.float64)
        if i < n - 1:
            m = z > 0
            masks.append(m)
            h = z * m
        else:
            f = float(z[0])
    g = np.asarray(weights[-1], dtype=np.float64)[:, 0]
    for i in range(n - 2, -1, -1):
        g = np.asarray(weights[i], dtype=np.float64) @ (g * masks[i])
    return f, g

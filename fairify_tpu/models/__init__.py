from fairify_tpu.models.mlp import MLP, forward, layer_outputs, predict
from fairify_tpu.models.ingest import load_keras_h5
from fairify_tpu.models import zoo

__all__ = ["MLP", "forward", "layer_outputs", "predict", "load_keras_h5", "zoo"]

"""Keras-compatible HDF5 export for repaired/synthetic models.

The reference's repair pipelines persist their outputs with
``model.save('AC-16.h5')`` (``src/AC/detect_bias.py:408``,
``src/AC/new_model.py:263``) so later drivers can verify them like any zoo
model.  This writer produces the same on-disk contract our own ingest (and
TF's loader) understands: a ``model_config`` attribute describing a
Sequential stack of Dense layers and ``model_weights/<name>/<name>/
{kernel,bias}:0`` datasets.
"""
from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from fairify_tpu.models.mlp import MLP


def save_keras_h5(net: MLP, path, name: str = "sequential") -> None:
    import h5py

    path = Path(path)
    n = net.depth
    layer_names = [f"dense_{i}" for i in range(n)]
    layers = [{
        "class_name": "InputLayer",
        "config": {"batch_input_shape": [None, net.in_dim], "dtype": "float32",
                   "name": "input_1"},
    }]
    for i, lname in enumerate(layer_names):
        layers.append({
            "class_name": "Dense",
            "config": {
                "name": lname,
                "units": int(net.weights[i].shape[1]),
                "activation": "relu" if i < n - 1 else "sigmoid",
                "use_bias": True,
                "dtype": "float32",
            },
        })
    cfg = {"class_name": "Sequential", "config": {"name": name, "layers": layers}}

    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(cfg)
        f.attrs["backend"] = "tensorflow"
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = np.array([s.encode() for s in layer_names])
        for i, lname in enumerate(layer_names):
            grp = mw.create_group(lname).create_group(lname)
            grp.create_dataset("kernel:0", data=np.asarray(net.weights[i], dtype=np.float32))
            grp.create_dataset("bias:0", data=np.asarray(net.biases[i], dtype=np.float32))
            mw[lname].attrs["weight_names"] = np.array(
                [f"{lname}/kernel:0".encode(), f"{lname}/bias:0".encode()]
            )

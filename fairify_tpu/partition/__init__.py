from fairify_tpu.partition.grid import (
    partition_attributes,
    partitioned_ranges,
    partition_attributes_capped,
    partitioned_ranges_capped,
    partition_density,
    boxes_from_partitions,
    coverage_fraction,
)

__all__ = [
    "partition_attributes",
    "partitioned_ranges",
    "partition_attributes_capped",
    "partitioned_ranges_capped",
    "partition_density",
    "boxes_from_partitions",
    "coverage_fraction",
]

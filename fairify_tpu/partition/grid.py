"""Input-domain partitioning: the scalability axis of the framework.

Semantics match the reference engine (``utils/input_partition.py``):

* ``partition_attributes`` chunks every attribute whose inclusive integer
  range is wider than the threshold into consecutive sub-ranges
  (``utils/input_partition.py:17-46``).
* ``partitioned_ranges`` takes the cartesian product of the chunked
  attributes, leaving narrow attributes at full range
  (``utils/input_partition.py:48-76``).
* the capped variant bounds combinatorial blow-up, partitioning protected
  attributes first and sampling excess combinations
  (``utils/input_partition.py:78-182``).
* ``partition_density`` is the dataset-coverage weight of each partition
  (``utils/input_partition.py:184-218``), vectorized here from a per-row
  Python scan to one broadcast comparison.

The output of the grid is a pair of integer arrays ``(lo, hi)`` of shape
``(P, d)`` — the box tensor that every downstream TPU kernel (IBP, CROWN,
simulation, branch-and-bound) consumes directly; partitions are rows, so
sharding the sweep over a device mesh is slicing this tensor along axis 0.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Sequence, Tuple

import numpy as np

Range = Tuple[int, int]
RangeDict = Dict[str, Sequence[int]]


def partition_attributes(range_dict: RangeDict, partition_size: int) -> Dict[str, List[Range]]:
    """Chunk each attribute range wider than ``partition_size`` (inclusive width)."""
    out: Dict[str, List[Range]] = {}
    for col, (low, high) in range_dict.items():
        width = high - low + 1
        if width <= partition_size:
            continue
        parts = []
        cur = low
        while cur <= high:
            parts.append((cur, min(cur + partition_size - 1, high)))
            cur += partition_size
        out[col] = parts
    return out


def partitioned_ranges(
    attrs: Sequence[str],
    p_dict: Dict[str, List[Range]],
    range_dict: RangeDict,
) -> List[RangeDict]:
    """Cartesian product of chunked attributes → list of box range-dicts."""
    base = {a: tuple(range_dict[a]) for a in attrs if a not in p_dict}
    chunked = list(p_dict.keys())
    boxes: List[RangeDict] = []
    for combo in itertools.product(*(p_dict[a] for a in chunked)):
        box = dict(base)
        for attr, rng in zip(chunked, combo):
            box[attr] = tuple(rng)
        boxes.append(box)
    return boxes


def partition_attributes_capped(range_dict: RangeDict, partition_size: int) -> Dict[str, List[Range]]:
    """Capped-variant chunking: width measured exclusively (``high - low``),
    as the DF driver does (``utils/input_partition.py:91-95``)."""
    out: Dict[str, List[Range]] = {}
    for col, (low, high) in range_dict.items():
        if high - low <= partition_size:
            continue
        parts = []
        cur = low
        while cur < high:
            parts.append((cur, min(cur + partition_size - 1, high)))
            cur = parts[-1][1] + 1
        if parts:
            out[col] = parts
    return out


def partitioned_ranges_capped(
    attrs: Sequence[str],
    protected: Sequence[str],
    p_dict: Dict[str, List[Range]],
    range_dict: RangeDict,
    max_partitions: int = 100,
    rng: np.random.Generator | None = None,
) -> List[RangeDict]:
    """Capped cartesian expansion, protected attributes first.

    Mirrors ``partitioned_ranges_df`` (``utils/input_partition.py:111-182``):
    include PA chunkings unconditionally, then add other chunked attributes
    while the product stays within ``max_partitions``; attributes left out
    keep their full range; if the product still overflows, sample
    ``max_partitions`` combinations (seeded generator here, not global
    ``random``, for reproducibility).
    """
    rng = rng or np.random.default_rng(0)
    base = {a: tuple(range_dict[a]) for a in attrs if a not in p_dict}
    if not p_dict:
        return [dict(base)]

    priority = [a for a in protected if a in p_dict]
    others = [a for a in p_dict if a not in priority]

    chosen: List[str] = []
    estimated = 1
    for a in priority:
        estimated *= len(p_dict[a])
        chosen.append(a)
    for a in others:
        if estimated * len(p_dict[a]) <= max_partitions:
            estimated *= len(p_dict[a])
            chosen.append(a)
        else:
            base[a] = tuple(range_dict[a])

    if not chosen:
        return [dict(base)]

    combos = list(itertools.product(*(p_dict[a] for a in chosen)))
    if len(combos) > max_partitions:
        idx = rng.choice(len(combos), size=max_partitions, replace=False)
        combos = [combos[i] for i in sorted(idx)]

    boxes = []
    for combo in combos:
        box = dict(base)
        for attr, rngpair in zip(chosen, combo):
            box[attr] = tuple(rngpair)
        boxes.append(box)
    return boxes


def boxes_from_partitions(p_list: Sequence[RangeDict], columns: Sequence[str]):
    """Stack a partition list into ``(lo, hi)`` int32 arrays of shape (P, d)."""
    lo = np.array([[p[c][0] for c in columns] for p in p_list], dtype=np.int32)
    hi = np.array([[p[c][1] for c in columns] for p in p_list], dtype=np.int32)
    return lo, hi


def partition_density(p_list: Sequence[RangeDict], X: np.ndarray, columns: Sequence[str]) -> np.ndarray:
    """Fraction of dataset rows falling inside each partition box.

    Vectorized replacement for the reference's per-row × per-partition Python
    scan (``utils/input_partition.py:198-218``): one broadcast comparison of
    the (N, d) data matrix against the (P, d) box tensor.
    """
    lo, hi = boxes_from_partitions(p_list, columns)
    Xv = np.asarray(X, dtype=np.float64)[None, :, :]  # (1, N, d)
    inside = (Xv >= lo[:, None, :]) & (Xv <= hi[:, None, :])  # (P, N, d)
    return inside.all(axis=2).mean(axis=1)


def coverage_fraction(p_list: Sequence[RangeDict], range_dict: RangeDict) -> float:
    """Fraction of the integer input domain covered by the partitions.

    Used for the Cov% column of the baseline table (BASELINE.md).
    """
    def box_volume(box: RangeDict) -> float:
        v = 1.0
        for lo, hi in box.values():
            v *= hi - lo + 1
        return v

    total = box_volume({k: tuple(v) for k, v in range_dict.items()})
    return float(sum(box_volume(p) for p in p_list) / total)


def chunk_spans(n: int, chunk: int):
    """(step, [(start, stop), ...]) fixed-`chunk` spans over n rows (0 = one span).

    Stage-0 kernels iterate the partition grid in these spans so device
    memory stays bounded on huge grids; every consumer (pruning, certify/
    attack, parity) must use the same spans.
    """
    if n == 0:
        return 0, []
    step = min(chunk, n) if chunk > 0 else n
    return step, [(s, min(n, s + step)) for s in range(0, n, step)]


def pad_rows(arr: np.ndarray, step: int) -> np.ndarray:
    """Repeat the last row so axis 0 reaches ``step`` (one static jit shape)."""
    arr = np.asarray(arr)
    if arr.shape[0] == step:
        return arr
    return np.concatenate(
        [arr, np.repeat(arr[-1:], step - arr.shape[0], axis=0)], axis=0)


def segment_spans(n: int, chunk: int, mega_chunks: int):
    """``(step, [(seg_start, seg_stop, [(s, e), ...]), ...])``.

    A *segment* is the mega-loop's launch unit: ``mega_chunks`` consecutive
    grid chunks certified by ONE device-resident ``lax.scan`` launch
    (DESIGN.md §17).  Chunk boundaries — and therefore every chunk-keyed
    RNG stream — are identical to :func:`chunk_spans` (this only groups
    them), so the segment grouping changes launch COUNT, never kernel
    inputs.  Shared by the stage-0/parity loops (verify/sweep.py) and the
    prune pass (verify/pruning.py) so their launch signatures cannot
    desync.
    """
    step, spans = chunk_spans(n, chunk)
    m = max(1, int(mega_chunks))
    segs = [(spans[i][0], spans[min(i + m, len(spans)) - 1][1],
             spans[i:i + m]) for i in range(0, len(spans), m)]
    return step, segs


def pad_chunk_axis(chunks, pad_chunks: int):
    """Segment chunk list padded to the segment bucket (last chunk repeated).

    A ragged FINAL segment (``len(spans) % mega_chunks != 0``) would
    otherwise scan a shorter chunk axis — a second XLA signature per mega
    kernel per model, exactly the shape churn the ragged-ROW pad
    (:func:`pad_rows`) already prevents.  Callers request padding only
    when the grid spans more than one segment (a single-segment run has
    one signature either way and padding it would multiply device work);
    decodes iterate the REAL chunk list, so padded iterations' outputs
    are never read.
    """
    if pad_chunks and len(chunks) < pad_chunks:
        return list(chunks) + [chunks[-1]] * (pad_chunks - len(chunks))
    return list(chunks)


class BoxList:
    """Lazy sequence view over a (P, d) box tensor as per-partition dicts.

    The cartesian grids of the stress/relaxed presets reach millions of
    partitions; materializing a Python dict per box costs gigabytes.  The
    sweep only needs ``len``/slicing, so boxes live as two arrays and the
    dict form (`{attr: (lo, hi)}`) is synthesized per access for the few
    callers that want it (density/coverage helpers, tests).
    """

    def __init__(self, lo: np.ndarray, hi: np.ndarray, columns):
        self.lo, self.hi, self.columns = lo, hi, tuple(columns)

    def __len__(self):
        return self.lo.shape[0]

    def __getitem__(self, i):
        if isinstance(i, slice):
            return BoxList(self.lo[i], self.hi[i], self.columns)
        return {c: (int(self.lo[i, j]), int(self.hi[i, j]))
                for j, c in enumerate(self.columns)}

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


def product_boxes(columns, p_dict: Dict[str, List[Range]], range_dict: RangeDict):
    """(lo, hi) arrays of the chunked-attribute cartesian product.

    Vectorized equivalent of ``partitioned_ranges`` +
    ``boxes_from_partitions`` with identical ordering (first chunked
    attribute slowest, matching ``itertools.product``), but O(P·d) array
    writes instead of P Python dicts.
    """
    columns = list(columns)
    chunked = list(p_dict.keys())
    sizes = [len(p_dict[a]) for a in chunked]
    P = int(np.prod(sizes)) if sizes else 1
    idx = np.indices(sizes).reshape(len(sizes), -1) if sizes else None
    lo = np.empty((P, len(columns)), dtype=np.int64)
    hi = np.empty((P, len(columns)), dtype=np.int64)
    for j, c in enumerate(columns):
        if c in p_dict:
            arr = np.asarray(p_dict[c], dtype=np.int64)
            k = chunked.index(c)
            lo[:, j] = arr[idx[k], 0]
            hi[:, j] = arr[idx[k], 1]
        else:
            lo[:, j], hi[:, j] = range_dict[c][0], range_dict[c][1]
    return lo, hi

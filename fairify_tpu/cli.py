"""Command-line driver: one entry point replacing the 21 reference scripts.

The reference is launched as ``./fairify.sh GC`` → ``python3 Verify-GC.py
[soft_timeout]`` (``src/fairify.sh:1-8``, ``INSTALL.md:36-49``).  Here:

    python -m fairify_tpu run GC                 # base German sweep
    python -m fairify_tpu run stress-BM --models BM-1 BM-2
    python -m fairify_tpu run relaxed-AC --soft-timeout 200
    python -m fairify_tpu list                   # preset inventory
    python -m fairify_tpu bench                  # headline benchmark

The positional soft-timeout override of the reference
(``src/GC/Verify-GC.py:146-147``) is the ``--soft-timeout`` flag.
"""
from __future__ import annotations

import argparse
import json
import sys


def _cmd_list(_args) -> int:
    from fairify_tpu.verify import presets

    for name in presets.names():
        cfg = presets.get(name)
        extras = []
        if cfg.relaxed:
            extras.append(f"RA={cfg.relaxed}@eps{cfg.relax_eps}")
        if cfg.domain_overrides:
            extras.append(f"targeted={cfg.domain_overrides}")
        print(f"{name:14s} dataset={cfg.dataset:8s} PA={cfg.protected} "
              f"thr={cfg.partition_threshold} {' '.join(extras)}")
    return 0


def _cmd_run(args) -> int:
    from fairify_tpu.verify import presets, sweep

    cfg = presets.get(args.preset)
    overrides = {}
    if args.soft_timeout is not None:
        overrides["soft_timeout_s"] = float(args.soft_timeout)
    if args.hard_timeout is not None:
        overrides["hard_timeout_s"] = float(args.hard_timeout)
    if args.models:
        overrides["models"] = tuple(args.models)
    if args.result_dir:
        overrides["result_dir"] = args.result_dir
    if args.seed is not None:
        overrides["seed"] = args.seed
    if overrides:
        cfg = cfg.with_(**overrides)

    mesh = None
    if args.mesh:
        from fairify_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()

    # --host-count distributes the partition grid: this process sweeps only
    # its contiguous slice (parallel.multihost.host_slice); span-qualified
    # ledgers merge across hosts with parallel.multihost.merge_ledgers.
    if (args.host_index is None) != (args.host_count is None):
        print("--host-index and --host-count must be given together", file=sys.stderr)
        return 2
    reports = sweep.run_sweep(cfg, model_root=args.model_root, data_root=args.data_root,
                              mesh=mesh, host_index=args.host_index,
                              host_count=args.host_count)
    if not reports:
        print(f"no models found for dataset {cfg.dataset!r} "
              f"(set --model-root or FAIRIFY_TPU_MODEL_ROOT)", file=sys.stderr)
        return 1
    for rep in reports:
        c = rep.counts
        host = {} if args.host_count is None else {"host": args.host_index}
        print(json.dumps({
            "model": rep.model, "dataset": rep.dataset, **host,
            "partitions": rep.partitions_total, "attempted": len(rep.outcomes),
            "sat": c["sat"], "unsat": c["unsat"], "unknown": c["unknown"],
            "original_acc": round(rep.original_acc, 4),
            "total_time_s": round(rep.total_time_s, 2),
        }))
    return 0


def _cmd_bench(_args) -> int:
    import bench

    bench.main()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fairify_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sub.add_parser("list", help="list sweep presets")

    run = sub.add_parser("run", help="run a verification sweep preset")
    run.add_argument("preset", help="preset name (see `list`)")
    run.add_argument("--models", nargs="*", help="restrict to these model names")
    run.add_argument("--soft-timeout", type=float, default=None)
    run.add_argument("--hard-timeout", type=float, default=None)
    run.add_argument("--result-dir", default=None)
    run.add_argument("--seed", type=int, default=None)
    run.add_argument("--model-root", default=None)
    run.add_argument("--data-root", default=None)
    run.add_argument("--host-index", type=int, default=None,
                     help="this process's index for multi-host partition distribution")
    run.add_argument("--host-count", type=int, default=None,
                     help="total hosts; each sweeps its slice of the grid")
    run.add_argument("--mesh", action="store_true",
                     help="shard stage 0 over all visible devices")

    sub.add_parser("bench", help="run the headline benchmark")

    args = ap.parse_args(argv)
    return {"list": _cmd_list, "run": _cmd_run, "bench": _cmd_bench}[args.cmd](args)


if __name__ == "__main__":
    raise SystemExit(main())

"""Command-line driver: one entry point replacing the 21 reference scripts.

The reference is launched as ``./fairify.sh GC`` → ``python3 Verify-GC.py
[soft_timeout]`` (``src/fairify.sh:1-8``, ``INSTALL.md:36-49``).  Here:

    python -m fairify_tpu run GC                 # base German sweep
    python -m fairify_tpu run stress-BM --models BM-1 BM-2
    python -m fairify_tpu run relaxed-AC --soft-timeout 200
    python -m fairify_tpu list                   # preset inventory
    python -m fairify_tpu bench                  # headline benchmark

The positional soft-timeout override of the reference
(``src/GC/Verify-GC.py:146-147``) is the ``--soft-timeout`` flag.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _finite(v):
    """JSON-strict numbers: non-finite floats (e.g. disparate impact with an
    all-negative privileged group) become null, never the bare `Infinity`
    token json.dumps would emit."""
    import math

    if isinstance(v, dict):
        return {k: _finite(x) for k, x in v.items()}
    if isinstance(v, float):
        return round(v, 5) if math.isfinite(v) else None
    return v


def _cmd_list(_args) -> int:
    from fairify_tpu.verify import presets

    for name in presets.names():
        cfg = presets.get(name)
        extras = []
        if cfg.relaxed:
            extras.append(f"RA={cfg.relaxed}@eps{cfg.relax_eps}")
        if cfg.domain_overrides:
            extras.append(f"targeted={cfg.domain_overrides}")
        print(f"{name:14s} dataset={cfg.dataset:8s} PA={cfg.protected} "
              f"thr={cfg.partition_threshold} {' '.join(extras)}")
    return 0


def _overridden_cfg(args):
    """Preset + the shared CLI override flags (run/experiment)."""
    from fairify_tpu.verify import presets

    cfg = presets.get(args.preset)
    overrides = {}
    if getattr(args, "soft_timeout", None) is not None:
        overrides["soft_timeout_s"] = float(args.soft_timeout)
    if getattr(args, "hard_timeout", None) is not None:
        overrides["hard_timeout_s"] = float(args.hard_timeout)
    if getattr(args, "models", None):
        overrides["models"] = tuple(args.models)
    if getattr(args, "result_dir", None):
        overrides["result_dir"] = args.result_dir
    if getattr(args, "seed", None) is not None:
        overrides["seed"] = args.seed
    if getattr(args, "max_partitions", None) is not None:
        # DF-style capped partitioning at an arbitrary cap
        # (``utils/input_partition.py:111-182`` with max_partitions=N).
        overrides["capped_partitions"] = True
        overrides["max_partitions"] = int(args.max_partitions)
    if getattr(args, "partition_metrics", False):
        overrides["partition_metrics"] = True
    if getattr(args, "trace_out", None):
        overrides["trace_out"] = args.trace_out
    if getattr(args, "xprof_dir", None):
        overrides["profile_dir"] = args.xprof_dir
    if getattr(args, "heartbeat_interval", None) is not None:
        overrides["heartbeat_s"] = float(args.heartbeat_interval)
    if getattr(args, "pipeline_depth", None) is not None:
        overrides["pipeline_depth"] = int(args.pipeline_depth)
    if getattr(args, "mega_chunks", None) is not None:
        overrides["mega_chunks"] = int(args.mega_chunks)
    if getattr(args, "max_launch_retries", None) is not None:
        overrides["max_launch_retries"] = int(args.max_launch_retries)
    if getattr(args, "launch_backoff", None) is not None:
        overrides["launch_backoff_s"] = float(args.launch_backoff)
    if getattr(args, "chunk_deadline", None) is not None:
        overrides["chunk_deadline_s"] = float(args.chunk_deadline)
    if getattr(args, "inject_fault", None):
        # Validate specs at the CLI boundary so a typo fails fast, not
        # mid-sweep when the schedule never fires.
        from fairify_tpu.resilience import faults

        faults.parse_specs(args.inject_fault)
        overrides["inject_faults"] = tuple(args.inject_fault)
    if getattr(args, "smt_retry", None):
        overrides["smt_retry_timeouts_s"] = tuple(
            float(t) for t in args.smt_retry)
    if getattr(args, "smt_workers", None) is not None:
        overrides["smt_workers"] = int(args.smt_workers)
    if getattr(args, "smt_memory_cap", None) is not None:
        overrides["smt_memory_cap_mb"] = int(args.smt_memory_cap)
    if getattr(args, "smt_portfolio", None) is not None:
        overrides["smt_portfolio"] = int(args.smt_portfolio)
    if getattr(args, "no_integrity", False):
        overrides["integrity"] = False
    if getattr(args, "integrity_recheck", None) is not None:
        rate = float(args.integrity_recheck)
        if not 0.0 <= rate <= 1.0:
            raise SystemExit("--integrity-recheck must be in [0, 1]")
        overrides["integrity_recheck"] = rate
    if getattr(args, "no_device_bab", False):
        overrides["device_bab"] = False
    # Engine-level BaB knobs ride the nested EngineConfig (DESIGN.md §22).
    eng_overrides = {}
    if getattr(args, "bab_frontier_cap", None) is not None:
        eng_overrides["bab_frontier_cap"] = int(args.bab_frontier_cap)
    if getattr(args, "bab_rounds", None) is not None:
        eng_overrides["bab_rounds_per_segment"] = int(args.bab_rounds)
    if eng_overrides:
        import dataclasses

        overrides["engine"] = dataclasses.replace(cfg.engine,
                                                  **eng_overrides)
    return cfg.with_(**overrides) if overrides else cfg


def _cmd_run(args) -> int:
    from fairify_tpu import obs

    cfg = _overridden_cfg(args)

    # CLI-level tracer scope: one event log + Chrome trace for the whole
    # sweep (the nested per-model scopes see the active tracer and no-op).
    with obs.tracing(cfg.trace_out, run_id=cfg.name):
        return _run_traced(args, cfg)


def _run_traced(args, cfg) -> int:
    from fairify_tpu.verify import sweep

    # --host-count distributes the partition grid: this process sweeps only
    # its contiguous slice (parallel.multihost.host_slice); span-qualified
    # ledgers merge across hosts with parallel.multihost.merge_ledgers.
    if (args.host_index is None) != (args.host_count is None):
        print("--host-index and --host-count must be given together", file=sys.stderr)
        return 2
    if args.shards is not None and args.host_count is not None:
        print("--shards and --host-count are mutually exclusive", file=sys.stderr)
        return 2
    if args.shards is not None and args.mesh:
        print("--shards and --mesh are mutually exclusive (each shard runs "
              "on its own submesh)", file=sys.stderr)
        return 2
    if args.shards is not None and args.shards < 1:
        print("--shards must be >= 1", file=sys.stderr)
        return 2
    if args.shards is not None and args.retry_unknown:
        print("--shards does not support --retry-unknown yet", file=sys.stderr)
        return 2
    mesh = None
    if args.mesh:
        from fairify_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
    reports = sweep.run_sweep(cfg, model_root=args.model_root, data_root=args.data_root,
                              mesh=mesh, host_index=args.host_index,
                              host_count=args.host_count,
                              retry_unknown=args.retry_unknown,
                              n_shards=args.shards)
    if not reports:
        print(f"no models found for dataset {cfg.dataset!r} "
              f"(set --model-root or FAIRIFY_TPU_MODEL_ROOT)", file=sys.stderr)
        return 1
    for rep in reports:
        c = rep.counts
        host = {} if args.host_count is None else {"host": args.host_index}
        if args.decode_counterexamples:
            # Decoded (raw-category) pair CSV, the reference's
            # ``decode_counterexample`` output
            # (``src/AC/Verify-AC-experiment-new2.py:383-407``).
            import os

            from fairify_tpu.analysis.decode import counterexample_table
            from fairify_tpu.data import loaders

            pairs = [o.counterexample for o in rep.outcomes if o.counterexample]
            if pairs:
                ds = loaders.load(cfg.dataset, root=args.data_root)
                table = counterexample_table(ds, pairs)
                out = os.path.join(
                    cfg.result_dir,
                    f"{rep.sink_name or rep.model}-counterexamples-decoded.csv")
                table.to_csv(out, index=False)
        print(json.dumps({
            "model": rep.model, "dataset": rep.dataset, **host,
            "partitions": rep.partitions_total, "attempted": len(rep.outcomes),
            "sat": c["sat"], "unsat": c["unsat"], "unknown": c["unknown"],
            "original_acc": round(rep.original_acc, 4),
            "total_time_s": round(rep.total_time_s, 2),
        }))
    return 0


def _cmd_bench(args) -> int:
    import bench

    bench.main(trace_out=getattr(args, "trace_out", None),
               heartbeat_s=float(getattr(args, "heartbeat_interval", None) or 0.0),
               xprof_dir=getattr(args, "xprof_dir", None))
    return 0


def _cmd_report(args) -> int:
    """Aggregate ``--trace-out`` event logs into phase/verdict/launch tables."""
    from fairify_tpu.obs import report

    logs = list(args.logs)
    if args.trace_dir:
        # A fleet's per-process shards ARE event logs: with no explicit
        # logs, the report aggregates every shard in the directory.
        from fairify_tpu.obs import trace as trace_mod

        shards = trace_mod.shard_paths(args.trace_dir)
        if not shards:
            print(f"report: no trace.<pid>.jsonl shards under "
                  f"{args.trace_dir}", file=sys.stderr)
            return 2
        if not logs:
            logs = shards
    elif not logs:
        print("report: give event logs or --trace-dir", file=sys.stderr)
        return 2
    return report.main(logs, json_out=args.json_out, as_json=args.json,
                       trace_dir=args.trace_dir, funnel=args.funnel)


def _cmd_experiment(args) -> int:
    """Verify → localize → repair → hybrid-route → audit, one model.

    The reference's experiment drivers + detect_bias/new_model scripts
    (``src/AC/Verify-AC-experiment-new2.py``, ``src/AC/detect_bias.py``,
    ``src/AC/new_model.py``) as one command.
    """
    from fairify_tpu import obs

    cfg = _overridden_cfg(args)
    with obs.tracing(cfg.trace_out, run_id=f"{cfg.name}-experiment"):
        return _experiment_traced(args, cfg)


def _experiment_traced(args, cfg) -> int:
    from fairify_tpu.analysis import experiment
    from fairify_tpu.data import loaders
    from fairify_tpu.models import zoo

    net = zoo.load(cfg.dataset, args.model, root=args.model_root)
    dataset = loaders.load(cfg.dataset, root=args.data_root)
    res = experiment.run_experiment(
        net, cfg, args.model, dataset=dataset, repair_mode=args.repair,
        causal_samples=args.causal_samples,
        verify_repaired=not args.no_verify_repaired)
    if args.save_fairer:
        from fairify_tpu.models import export

        # The reference's repaired-model artifact (AC-16.h5 analog,
        # ``src/AC/detect_bias.py:408``) in Keras-compatible HDF5.
        export.save_keras_h5(res.fairer_net, args.save_fairer)
    out = {
        "model": args.model,
        "verdicts": res.report.counts,
        "counterexample_pairs": len(res.ce_pairs),
        "biased_neurons": ([[l, j, round(float(s), 5)]
                            for l, j, s in res.localization.ranked]
                           if res.localization else []),
        "metrics": _finite(res.metrics),
        "causal_rates": _finite(res.causal_rates),
        "fairer_verdicts": res.fairer_verdicts,
        "routing": res.routing,
        "success": res.success,
        "saved_fairer": args.save_fairer or None,
    }
    print(json.dumps(out))
    if args.json_out:
        with open(args.json_out, "w") as fp:
            json.dump(out, fp)
    return 0


def _cmd_serve(args) -> int:
    """Run the persistent verification server (DESIGN.md §13).

    Owns the device and its warm ``obs_jit`` kernel cache for its whole
    lifetime; requests arrive through the spool inbox (``fairify_tpu
    submit``) and coalesce into shared launches.  SIGTERM/SIGINT drain
    gracefully: in-flight work finishes, queued requests are journaled
    back to the inbox for the next server's ``resume=True`` pickup.
    """
    import signal
    import threading

    from fairify_tpu import obs
    from fairify_tpu.serve import FleetConfig, ServeConfig, ServerFleet, \
        VerificationServer

    exec_cache = args.exec_cache
    if exec_cache == "auto":
        exec_cache = os.path.join(args.spool, "exec-cache")
    elif exec_cache in ("off", "none", ""):
        exec_cache = None
    scfg = ServeConfig(
        spool=args.spool, batch_window_s=args.batch_window,
        max_batch=args.max_batch, span_chunks=args.span_chunks,
        poll_s=args.poll_interval, default_deadline_s=args.default_deadline,
        n_shards=args.shards, smt_workers=args.smt_workers,
        smt_memory_cap_mb=args.smt_memory_cap,
        smt_portfolio=args.smt_portfolio,
        max_queue=args.max_queue, preempt_factor=args.preempt_factor,
        fair_share_factor=args.fair_share,
        fair_share_idle_exempt=not args.fair_share_strict,
        exec_cache=exec_cache, trace_dir=args.trace_dir,
        xprof_dir=args.xprof_dir)
    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    if args.replica_procs and (args.replicas > 1 or args.shards):
        print("serve: --replica-procs is mutually exclusive with "
              "--replicas/--shards", file=sys.stderr)
        return 2
    # --trace-dir puts THIS process's spans in its own pid-named shard
    # next to the replicas' and SMT workers' shards; --trace-out keeps the
    # single-file behavior.  The shard wins when both are given.
    trace_out = args.trace_out
    if args.trace_dir:
        from fairify_tpu.obs import trace as trace_mod

        trace_out = trace_mod.shard_path(args.trace_dir)
    with obs.tracing(trace_out, run_id="serve"):
        if args.replica_procs and args.replica_procs >= 1:
            from dataclasses import replace

            from fairify_tpu.serve import ProcessFleet, ProcFleetConfig

            srv = ProcessFleet(ProcFleetConfig(
                n_replicas=args.replica_procs, spool=args.spool,
                poll_s=args.poll_interval, lease_s=args.lease,
                memory_cap_mb=args.replica_memory_cap,
                max_restarts=args.max_restarts,
                exec_cache=exec_cache, trace_dir=args.trace_dir,
                replica=replace(scfg, spool=None, exec_cache=None,
                                trace_dir=None))).start()
        elif args.replicas and args.replicas > 1:
            from dataclasses import replace

            srv = ServerFleet(FleetConfig(
                n_replicas=args.replicas, spool=args.spool,
                poll_s=args.poll_interval, lease_s=args.lease,
                replica=replace(scfg, spool=None))).start()
        else:
            srv = VerificationServer(scfg).start()
        print(f"fairify_tpu serve: spool={args.spool} "
              f"batch_window={scfg.batch_window_s}s max_batch={scfg.max_batch}"
              f" replicas={args.replica_procs or args.replicas or 1}"
              f"{' (processes)' if args.replica_procs else ''}"
              f" exec_cache={exec_cache or 'off'}"
              f" (SIGTERM drains)", file=sys.stderr)
        worker_died = False
        while not stop.wait(timeout=1.0):
            if not srv.alive():
                # A propagate-class crash killed the worker (or the whole
                # fleet); without this check the process would advertise a
                # live server whose inbox is never scanned again.
                worker_died = True
                print("fairify_tpu serve: worker thread died — draining",
                      file=sys.stderr)
                break
        requeued = srv.drain()
    print(json.dumps({"drained": True, "worker_died": worker_died,
                      "requeued": [r if isinstance(r, str) else r.id
                                   for r in requeued]}))
    return 1 if worker_died else 0


def _cmd_submit(args) -> int:
    """Submit one verification job to a running server's spool."""
    from fairify_tpu.serve import client

    overrides = {}
    if args.soft_timeout is not None:
        overrides["soft_timeout_s"] = float(args.soft_timeout)
    if args.hard_timeout is not None:
        overrides["hard_timeout_s"] = float(args.hard_timeout)
    if args.seed is not None:
        overrides["seed"] = int(args.seed)
    if args.grid_chunk is not None:
        overrides["grid_chunk"] = int(args.grid_chunk)
    init = None
    if args.init_sizes:
        init = {"sizes": args.init_sizes, "seed": args.init_seed}
    try:
        payload = client.build_payload(
            args.preset, model=args.model, init=init,
            overrides=overrides or None, deadline_s=args.deadline,
            span=tuple(args.span) if args.span else None,
            model_root=args.model_root, priority=args.priority)
    except ValueError as exc:
        print(f"submit: {exc}", file=sys.stderr)
        return 2
    req_id = client.submit(args.spool, payload)
    if args.wait is None:
        print(json.dumps({"request": req_id, "status": "submitted"}))
        return 0
    rec = client.wait(args.spool, req_id,
                      timeout=args.wait if args.wait > 0 else None)
    if rec is None:
        print(json.dumps({"request": req_id, "status": "pending"}))
        return 3
    print(json.dumps(rec))
    return 0 if rec.get("status") == "done" else 1


def _cmd_lint(args) -> int:
    """Run the static-analysis rule engine (DESIGN.md §11) over fairify_tpu/."""
    from fairify_tpu.lint import core as lint_core

    return lint_core.run_cli(args)


def _cmd_metrics(args) -> int:
    """Group-fairness report for zoo models on their dataset's test split
    (the reference's AIF360 metric blocks, ``src/CP/Verify-CP.py:398-458``)."""
    import numpy as np

    from fairify_tpu.analysis import metrics as gm
    from fairify_tpu.data import loaders
    from fairify_tpu.models import mlp as mlp_mod
    from fairify_tpu.models import zoo
    from fairify_tpu.verify import presets

    import jax.numpy as jnp

    cfg = presets.get(args.preset)
    ds = loaders.load(cfg.dataset, root=args.data_root)
    pa = cfg.query().protected[0]
    pa_col = list(cfg.query().columns).index(pa)
    nets, skipped = zoo.load_matching(
        cfg.dataset, ds.X_test.shape[1],
        models=tuple(args.models) if args.models else None,
        root=args.model_root)
    for name, net in nets.items():
        pred = np.asarray(
            mlp_mod.predict(net, jnp.asarray(ds.X_test, jnp.float32))).astype(int)
        rep = gm.group_report(ds.X_test, ds.y_test, pred,
                              ds.X_test[:, pa_col]).as_dict()
        print(json.dumps({"model": name, "protected": pa, **_finite(rep)}))
    if nets:
        return 0
    if skipped:
        print(f"all candidate models skipped (input dim != "
              f"{ds.X_test.shape[1]}): {skipped}", file=sys.stderr)
    elif args.models:
        avail = [p.stem for p in zoo.model_paths(cfg.dataset, root=args.model_root)]
        print(f"no zoo model matched --models {args.models} for dataset "
              f"{cfg.dataset!r} (available: {avail})", file=sys.stderr)
    else:
        print(f"no models found for dataset {cfg.dataset!r} "
              f"(set --model-root or FAIRIFY_TPU_MODEL_ROOT)", file=sys.stderr)
    return 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fairify_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sub.add_parser("list", help="list sweep presets")

    run = sub.add_parser("run", help="run a verification sweep preset")
    run.add_argument("preset", help="preset name (see `list`)")
    run.add_argument("--models", nargs="*", help="restrict to these model names")
    run.add_argument("--soft-timeout", type=float, default=None)
    run.add_argument("--hard-timeout", type=float, default=None)
    run.add_argument("--result-dir", default=None)
    run.add_argument("--seed", type=int, default=None)
    run.add_argument("--max-partitions", type=int, default=None,
                     help="cap the grid via DF-style capped partitioning "
                          "(PA-first priority, sampled excess combos)")
    run.add_argument("--model-root", default=None)
    run.add_argument("--data-root", default=None)
    run.add_argument("--decode-counterexamples", action="store_true",
                     help="also write raw-category decoded counterexample CSVs")
    run.add_argument("--partition-metrics", action="store_true",
                     help="emit <model>-metrics.csv per partition "
                          "(src/CP/Verify-CP.py:398-458 artifact shape)")
    run.add_argument("--retry-unknown", action="store_true",
                     help="re-attempt partitions a previous run left UNKNOWN")
    run.add_argument("--host-index", type=int, default=None,
                     help="this process's index for multi-host partition distribution")
    run.add_argument("--host-count", type=int, default=None,
                     help="total hosts; each sweeps its slice of the grid")
    run.add_argument("--mesh", action="store_true",
                     help="shard stage 0 over all visible devices")
    run.add_argument("--shards", type=int, default=None,
                     help="fault-tolerant sharded sweep: split the grid "
                          "into N per-shard fault domains over the visible "
                          "devices; a shard loss elastically re-shards onto "
                          "the survivors (parallel.shards)")
    run.add_argument("--trace-out", default=None,
                     help="write a JSONL span/event log here plus a Chrome "
                          "trace alongside (<path>.chrome.json)")
    run.add_argument("--xprof-dir", default=None, metavar="DIR",
                     help="capture an XLA profiler trace of the device "
                          "phases here (TensorBoard/XProf; device-timeline "
                          "annotations share the obs span names)")
    run.add_argument("--pipeline-depth", type=int, default=None,
                     help="async launch pipeline depth (chunk launches kept "
                          "in flight; 1 = synchronous, default 2)")
    run.add_argument("--mega-chunks", type=int, default=None,
                     help="grid chunks per device-resident mega launch: one "
                          "lax.scan launch certifies this many chunks "
                          "(segment = the fault blast radius and the "
                          "supervisor's retry unit; default 4, 0 = "
                          "per-chunk launches)")
    run.add_argument("--no-device-bab", action="store_true",
                     help="fall back to the host-frontier BaB loop "
                          "(verdicts are bit-equal; the device queue only "
                          "changes the launch economy — DESIGN.md §22)")
    run.add_argument("--bab-frontier-cap", type=int, default=None,
                     help="device BaB box-queue capacity (slots shared by "
                          "a root group; default 512, floor 4).  Roots "
                          "that stall overflowed report "
                          "unknown:frontier:overflow — raise this knob")
    run.add_argument("--bab-rounds", type=int, default=None,
                     help="branching rounds per device BaB launch "
                          "(lax.scan trip count; default 8).  Launches "
                          "per root are O(rounds-needed / this)")
    run.add_argument("--heartbeat-interval", type=float, default=None,
                     help="stderr progress line every N seconds (0 = off)")
    run.add_argument("--max-launch-retries", type=int, default=None,
                     help="transient-fault retries per chunk before its "
                          "partitions degrade to UNKNOWN-with-reason "
                          "(default 2)")
    run.add_argument("--launch-backoff", type=float, default=None,
                     help="first-retry backoff seconds (exponential, "
                          "jittered; default 0.05)")
    run.add_argument("--chunk-deadline", type=float, default=None,
                     help="per-chunk retry deadline in seconds (0 = off): "
                          "no retry starts after a chunk has spent this long")
    run.add_argument("--inject-fault", action="append", default=None,
                     metavar="SITE:KIND:NTH",
                     help="chaos testing: schedule a fault, e.g. "
                          "launch.submit:transient:3, compile:crash:1, or "
                          "launch.decode:corrupt:2 (silent bit-flip; "
                          "repeatable; sites: launch.submit launch.decode "
                          "compile smt.query ledger.append "
                          "smt.worker.{spawn,crash,hang,memout} ...)")
    run.add_argument("--smt-retry", type=float, nargs="*", default=None,
                     metavar="S",
                     help="escalating SMT timeout ladder in seconds (e.g. "
                          "--smt-retry 300 900): enables the out-of-process "
                          "solver tier for UNKNOWN boxes (DESIGN.md §14)")
    run.add_argument("--smt-workers", type=int, default=None,
                     help="SMT solver worker subprocesses; UNKNOWN boxes "
                          "fan out across all of them (default 1)")
    run.add_argument("--smt-memory-cap", type=int, default=None,
                     metavar="MB",
                     help="RLIMIT_AS per SMT worker in MB (0 = uncapped); "
                          "a memout retries once on a doubled cap")
    run.add_argument("--smt-portfolio", type=int, default=None,
                     metavar="K",
                     help="race K solver seed variants per SMT query and "
                          "take the first decisive answer (0/1 = off)")
    run.add_argument("--integrity-recheck", type=float, default=None,
                     metavar="RATE",
                     help="sampled-recheck rate in [0,1]: re-execute this "
                          "fraction of decided chunks (bit-equality "
                          "required) and escalate a sample of certified / "
                          "SMT-unsat verdicts to the exact-rational oracle "
                          "(default 0; 0.05 is the benched operating point)")
    run.add_argument("--no-integrity", action="store_true",
                     help="disable the always-on SDC detectors (canary "
                          "chunk, fold checksum, ledger row CRC) — A/B "
                          "debugging only, DESIGN.md §21")

    ben = sub.add_parser("bench", help="run the headline benchmark")
    ben.add_argument("--trace-out", default=None,
                     help="JSONL span/event log for the timed headline run")
    ben.add_argument("--heartbeat-interval", type=float, default=None,
                     help="stderr progress line every N seconds (0 = off)")
    ben.add_argument("--xprof-dir", default=None, metavar="DIR",
                     help="capture an XLA profiler trace of the final timed "
                          "headline repeat here (TensorBoard/XProf)")

    rpt = sub.add_parser(
        "report", help="aggregate --trace-out event logs into phase/verdict/"
                       "launch breakdown tables")
    rpt.add_argument("logs", nargs="*", help="one or more JSONL event logs")
    rpt.add_argument("--json", action="store_true",
                     help="print the aggregate as one JSON line instead of tables")
    rpt.add_argument("--json-out", default=None,
                     help="also write the aggregate JSON to this file")
    rpt.add_argument("--funnel", action="store_true",
                     help="also print the verification-funnel tables: "
                          "terminal-state counts, stage-0 margin/gap "
                          "histograms, per-layer bound-looseness "
                          "attribution (DESIGN.md §20)")
    rpt.add_argument("--trace-dir", default=None,
                     help="fleet trace-shard directory (serve --trace-dir): "
                          "merges every trace.<pid>.jsonl into one Perfetto "
                          "export (<dir>/merged.chrome.json) and prints the "
                          "per-request critical-path table")

    exp = sub.add_parser(
        "experiment", help="verify + localize + repair + hybrid-route + audit")
    exp.add_argument("preset")
    exp.add_argument("--model", required=True)
    exp.add_argument("--repair", choices=("masked", "retrain", "both"),
                     default="retrain")
    exp.add_argument("--causal-samples", type=int, default=2000)
    exp.add_argument("--no-verify-repaired", action="store_true",
                     help="skip re-verifying the repaired model's grid")
    exp.add_argument("--soft-timeout", type=float, default=None)
    exp.add_argument("--hard-timeout", type=float, default=None)
    exp.add_argument("--result-dir", default=None)
    exp.add_argument("--model-root", default=None)
    exp.add_argument("--data-root", default=None)
    exp.add_argument("--seed", type=int, default=None)
    exp.add_argument("--json-out", default=None,
                     help="also write the summary JSON to this file")
    exp.add_argument("--save-fairer", default=None,
                     help="write the repaired model as Keras-compatible .h5")
    exp.add_argument("--pipeline-depth", type=int, default=None,
                     help="async launch pipeline depth (1 = synchronous)")
    exp.add_argument("--trace-out", default=None,
                     help="write a JSONL span/event log here plus a Chrome "
                          "trace alongside (<path>.chrome.json)")
    exp.add_argument("--heartbeat-interval", type=float, default=None,
                     help="stderr progress line every N seconds (0 = off)")

    met = sub.add_parser("metrics", help="group-fairness report per zoo model")
    met.add_argument("preset")
    met.add_argument("--models", nargs="*")
    met.add_argument("--model-root", default=None)
    met.add_argument("--data-root", default=None)

    srv = sub.add_parser(
        "serve", help="persistent verification server: warm kernel cache, "
                      "cross-request batching, SLA-aware admission "
                      "(DESIGN.md §13)")
    srv.add_argument("--spool", required=True,
                     help="service directory: inbox/ for submits, "
                          "requests/<id>/ for results, serve.journal.jsonl "
                          "for lifecycle records")
    srv.add_argument("--batch-window", type=float, default=0.05,
                     help="coalescing window after the first queued request "
                          "(seconds; default 0.05)")
    srv.add_argument("--max-batch", type=int, default=8,
                     help="most requests coalesced per batch (default 8)")
    srv.add_argument("--span-chunks", type=int, default=0,
                     help="refinement granule in grid chunks: 0 = one "
                          "verify_model call per request, N = yield every "
                          "N chunks so drain/deadline checks interleave "
                          "mid-request")
    srv.add_argument("--poll-interval", type=float, default=0.1,
                     help="inbox scan interval (seconds; default 0.1)")
    srv.add_argument("--default-deadline", type=float, default=None,
                     help="SLA applied to submits that carry none "
                          "(seconds; default: best effort)")
    srv.add_argument("--shards", type=int, default=None,
                     help="route requests through the fault-tolerant shard "
                          "fleet (parallel.shards) instead of the "
                          "single-mesh sweep")
    srv.add_argument("--replicas", type=int, default=1,
                     help="run N server replicas behind an arch-bucket "
                          "router with heartbeat failover (serve.fleet; "
                          "default 1 = single server)")
    srv.add_argument("--lease", type=float, default=0.0,
                     help="replica heartbeat lease in seconds (fleet mode): "
                          "a worker silent past the lease is declared lost "
                          "and failed over (0 = thread-liveness only; with "
                          "--replica-procs this is the FILE-lease hang "
                          "deadline answered by SIGTERM->SIGKILL)")
    srv.add_argument("--replica-procs", type=int, default=0,
                     help="run N replicas as real OS processes "
                          "(serve.procfleet, DESIGN.md §18): hard-kill "
                          "containment, lease-based hang detection, "
                          "loss-free cross-process failover; mutually "
                          "exclusive with --replicas/--shards")
    srv.add_argument("--replica-memory-cap", type=int, default=0,
                     metavar="MB",
                     help="RLIMIT_AS per replica PROCESS in MB "
                          "(--replica-procs mode; a memory blowup kills "
                          "one replica, not the fleet; 0 = uncapped)")
    srv.add_argument("--max-restarts", type=int, default=3,
                     help="bounded restart budget per replica-process slot "
                          "(--replica-procs mode; exhausted slots are "
                          "abandoned and their work re-homed)")
    srv.add_argument("--max-queue", type=int, default=0,
                     help="bounded queue: shed (reject with a machine-"
                          "readable 'shed:' reason) submits past this "
                          "depth, scaled by priority headroom (0 = "
                          "unbounded)")
    srv.add_argument("--preempt-factor", type=float, default=0.0,
                     help="preempt a running request at its next span "
                          "granule once it exceeds this multiple of its "
                          "admission estimate and higher-priority work "
                          "waits (needs --span-chunks > 0; 0 = off)")
    srv.add_argument("--fair-share", type=float, default=0.0,
                     help="under contention, clamp a request's hard "
                          "refinement budget to this multiple of its "
                          "admission estimate — overrun becomes honest "
                          "budget-exhausted UNKNOWNs (resumable) instead "
                          "of tail latency (0 = off)")
    srv.add_argument("--fair-share-strict", action="store_true",
                     help="clamp EVERY dispatch (not just contended ones) "
                          "to its fair share: the latency-predictable "
                          "tier — exhaustive refinement belongs to batch "
                          "runs")
    srv.add_argument("--exec-cache", default="auto", metavar="DIR",
                     help="persistent executable cache directory: fresh "
                          "replicas/restarts load AOT-serialized "
                          "executables instead of recompiling "
                          "('auto' = <spool>/exec-cache, 'off' disables)")
    srv.add_argument("--trace-out", default=None,
                     help="JSONL span/event log (request lifecycle events "
                          "feed the `fairify_tpu report` request table)")
    srv.add_argument("--trace-dir", default=None, metavar="DIR",
                     help="fleet-wide trace shards (DESIGN.md §19): every "
                          "process — router, replicas, SMT workers — "
                          "appends spans to its own trace.<pid>.jsonl "
                          "here; `fairify_tpu report --trace-dir DIR` "
                          "merges them into one Perfetto timeline with "
                          "per-request critical paths")
    srv.add_argument("--xprof-dir", default=None, metavar="DIR",
                     help="capture XLA profiler traces of every request's "
                          "device phases here (TensorBoard/XProf)")
    srv.add_argument("--smt-workers", type=int, default=1,
                     help="server-wide SMT worker pool size shared by every "
                          "SMT-enabled request (default 1)")
    srv.add_argument("--smt-memory-cap", type=int, default=0, metavar="MB",
                     help="RLIMIT_AS per SMT worker in MB (0 = uncapped)")
    srv.add_argument("--smt-portfolio", type=int, default=0, metavar="K",
                     help="race K solver seed variants per SMT query "
                          "(0/1 = off)")

    sbm = sub.add_parser(
        "submit", help="submit one verification job to a running server")
    sbm.add_argument("preset", help="preset name (see `list`)")
    sbm.add_argument("--spool", required=True,
                     help="the server's --spool directory")
    sbm.add_argument("--model", default=None,
                     help="zoo model name (e.g. GC-1)")
    sbm.add_argument("--priority", default=None,
                     choices=["low", "normal", "high"],
                     help="scheduling tier: higher pops first, sheds last, "
                          "and may preempt a running lower tier "
                          "(default: normal)")
    sbm.add_argument("--init-sizes", type=int, nargs="*", default=None,
                     metavar="N",
                     help="synthetic net layer sizes instead of --model "
                          "(e.g. --init-sizes 20 8 1)")
    sbm.add_argument("--init-seed", type=int, default=0)
    sbm.add_argument("--deadline", type=float, default=None,
                     help="wall-clock SLA in seconds from submit")
    sbm.add_argument("--span", type=int, nargs=2, default=None,
                     metavar=("START", "STOP"),
                     help="global partition span [START, STOP)")
    sbm.add_argument("--soft-timeout", type=float, default=None)
    sbm.add_argument("--hard-timeout", type=float, default=None)
    sbm.add_argument("--seed", type=int, default=None)
    sbm.add_argument("--grid-chunk", type=int, default=None)
    sbm.add_argument("--model-root", default=None)
    sbm.add_argument("--wait", type=float, default=None, nargs="?", const=0.0,
                     metavar="TIMEOUT",
                     help="block until the verdict lands (optional timeout "
                          "in seconds; bare --wait waits forever); exit 0 "
                          "iff the request finished `done`")

    lint = sub.add_parser(
        "lint", help="run the static-analysis engine over fairify_tpu/: "
                     "nine AST rules by default, the four jaxpr/IR passes "
                     "over the obs_jit kernel registry with --ir "
                     "(DESIGN.md §11)")
    from fairify_tpu.lint.core import add_cli_args as _lint_cli_args

    _lint_cli_args(lint)

    args = ap.parse_args(argv)
    return {"list": _cmd_list, "run": _cmd_run, "bench": _cmd_bench,
            "experiment": _cmd_experiment, "metrics": _cmd_metrics,
            "report": _cmd_report, "lint": _cmd_lint,
            "serve": _cmd_serve, "submit": _cmd_submit}[args.cmd](args)


if __name__ == "__main__":
    raise SystemExit(main())

"""Structured span tracer: nested, thread-safe spans → JSONL + Chrome trace.

One :class:`Tracer` per run (usually activated by the CLI's ``--trace-out``
or ``SweepConfig.trace_out``).  Instrumented code never holds a tracer —
it calls the module-level :func:`span` / :func:`event`, which route to the
active tracer or to a shared no-op when tracing is off, so the disabled
path costs one global read per span (the acceptance bar: no measurable
overhead on the bench numbers).

Event-log schema (one JSON object per line, append-only and crash-safe
like the verdict ledgers; truncated trailing lines are tolerated on read):

* ``{"type": "meta", "version": 1, "run_id": ..., "wall_time": ...}`` —
  written once per tracer activation.
* ``{"type": "span", "name", "span_id", "parent_id", "tid", "ts",
  "dur_s", "attrs"}`` — written when a span closes.  ``ts`` is wall-clock
  epoch seconds at span start (so logs from sequential runs appended to
  one file stay ordered); ``dur_s`` is a monotonic perf-counter delta.
  Spans that covered device work carry an automatic ``launches`` attr —
  the delta of the ``device_launches`` counter over the span.
* ``{"type": "event", "name", "ts", "tid", "attrs"}`` — instant events
  (per-partition verdicts, retries).
* ``{"type": "metrics", "ts", "metrics"}`` — the run's registry delta
  (:func:`fairify_tpu.obs.metrics.snapshot_delta` of activation-time vs
  close-time snapshots, so a warm-up pass or earlier run in the same
  process never pollutes it), appended when the tracer closes.

:func:`write_chrome_trace` converts an event log into the Chrome
``traceEvents`` JSON that ``chrome://tracing`` / Perfetto load directly;
:mod:`fairify_tpu.obs.report` aggregates the same log into tables.

This module is the obs layer's clock shim: it is the one place allowed to
call ``time.time()`` (wall-clock span timestamps) — everything else goes
through spans (the ``obs-time-time`` lint rule enforces it).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import List, Optional

from fairify_tpu.obs import metrics as metrics_mod

EVENT_VERSION = 1


def _round(v: float, nd: int = 6) -> float:
    # Raw floats internally, rounding only at serialization (the PhaseTimer
    # 2-vs-3-decimal inconsistency this layer replaces).
    return round(float(v), nd)


class _NullSpan:
    """Shared do-nothing span for the tracing-disabled path."""

    __slots__ = ()

    def set(self, **attrs) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


# ---------------------------------------------------------------------------
# Trace context: the cross-boundary identity of one request
# ---------------------------------------------------------------------------


class TraceContext:
    """The per-request identity that crosses process boundaries.

    ``trace_id`` is stamped once, at submit time (``serve/client.py``);
    ``parent_span`` is the sender-side span id the receiving process
    should treat as its logical parent.  Span ids are per-process
    counters, so ``parent_span`` is only meaningful together with the
    sender's shard — the merged view namespaces tracks by ``(pid, tid)``
    and joins shards on ``trace_id``, never on raw span ids.
    """

    __slots__ = ("trace_id", "parent_span")

    def __init__(self, trace_id: str, parent_span: Optional[int] = None):
        self.trace_id = str(trace_id)
        self.parent_span = parent_span

    def fields(self) -> dict:
        """The wire form: ``{"trace": {"id": ..., "span": ...}}`` —
        mergeable into any JSON frame (spool payload, pipe frame, SMT
        query frame) without schema changes on the reader side."""
        t: dict = {"id": self.trace_id}
        if self.parent_span is not None:
            t["span"] = int(self.parent_span)
        return {"trace": t}

    @staticmethod
    def from_fields(obj: Optional[dict]) -> Optional["TraceContext"]:
        """Recover a context from a frame's ``trace`` field (None when
        the frame predates tracing or came from a trace-off sender)."""
        t = (obj or {}).get("trace")
        if not isinstance(t, dict) or not t.get("id"):
            return None
        span_id = t.get("span")
        return TraceContext(str(t["id"]),
                            int(span_id) if span_id is not None else None)


def new_trace_id() -> str:
    """A fresh 64-bit hex trace id (collision-safe across processes)."""
    return os.urandom(8).hex()


_ctx_tls = threading.local()


def current_context() -> Optional[TraceContext]:
    """The trace context bound to this thread (None outside any request)."""
    return getattr(_ctx_tls, "ctx", None)


class _ContextScope:
    """Bind a context for a scope; restores the previous binding on exit.

    A ``None`` context is a no-op scope (the caller can bind
    ``TraceContext.from_fields(frame)`` unconditionally)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self) -> Optional[TraceContext]:
        self._prev = getattr(_ctx_tls, "ctx", None)
        if self._ctx is not None:
            _ctx_tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _ctx_tls.ctx = self._prev
        return False


def context(ctx: Optional[TraceContext]) -> _ContextScope:
    """``with context(ctx): ...`` — every span/event/outgoing frame in the
    scope records/carries ``ctx``.  Nested scopes shadow; threads never
    inherit (a handoff must capture :func:`current_context` explicitly
    and re-bind on the far side — the queues in serve/ and parallel/ do
    exactly that)."""
    return _ContextScope(ctx)


def context_fields() -> dict:
    """Trace fields for an outgoing cross-boundary frame.

    ``{}`` when no context is bound (control frames — ping/drain/hello —
    legitimately carry none).  The ``span`` field is the innermost open
    span on this thread when tracing is active, so the receiver's shard
    records which sender-side stage handed the work over."""
    ctx = current_context()
    if ctx is None:
        return {}
    parent = ctx.parent_span
    tr = _active
    if tr is not None:
        stack = tr._stack()
        if stack:
            parent = stack[-1].span_id
    return TraceContext(ctx.trace_id, parent).fields()


class Span:
    """One open span; created by :meth:`Tracer.span`, closed on ``__exit__``."""

    __slots__ = ("name", "attrs", "span_id", "parent_id", "tid",
                 "_tracer", "_t0", "_ts", "_launch0", "_trace")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self._tracer = tracer
        self.span_id = next(tracer._ids)
        self.parent_id: Optional[int] = None
        self.tid = 0

    def set(self, **attrs) -> None:
        """Attach/overwrite attributes on the open span."""
        self.attrs.update(attrs)

    def __enter__(self):
        tr = self._tracer
        stack = tr._stack()
        self.parent_id = stack[-1].span_id if stack else None
        self.tid = tr._tid()
        self._trace = current_context()
        self._ts = time.time()
        self._launch0 = tr._launches()
        self._t0 = time.perf_counter()
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        tr = self._tracer
        stack = tr._stack()
        if stack and stack[-1] is self:
            stack.pop()
        launches = tr._launches() - self._launch0
        if launches > 0:
            self.attrs.setdefault("launches", int(launches))
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        rec = {
            "type": "span", "name": self.name, "span_id": self.span_id,
            "parent_id": self.parent_id, "tid": self.tid,
            "ts": _round(self._ts), "dur_s": _round(dur),
            "attrs": self.attrs,
        }
        if self._trace is not None:
            rec["trace_id"] = self._trace.trace_id
            if self.parent_id is None \
                    and self._trace.parent_span is not None:
                # Cross-process parent: the sender-side span id that handed
                # this work over (meaningful only with the sender's shard).
                rec["remote_parent"] = int(self._trace.parent_span)
        tr._write(rec)
        return False


class Tracer:
    """Appends span/event records to a JSONL file, one line per record."""

    def __init__(self, path: str, run_id: Optional[str] = None):
        self.path = path
        self.run_id = run_id
        parent = os.path.dirname(path)
        if parent:  # e.g. --trace-out inside a result_dir not yet created
            os.makedirs(parent, exist_ok=True)
        self._fp = open(path, "a")
        self._write_lock = threading.Lock()
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._tid_lock = threading.Lock()
        self._tid_map: dict = {}
        self._closed = False
        # Baseline for the closing per-run metrics delta: the process
        # registry is cumulative (a warm-up sweep or a previous run in the
        # same process has already bumped it).
        self._metrics0 = metrics_mod.registry().snapshot()
        # ``pid`` namespaces this shard's tracks in merged views: thread
        # ids are only unique per process, so two replicas' worker threads
        # would otherwise interleave on one Perfetto track.
        self._write({"type": "meta", "version": EVENT_VERSION,
                     "run_id": run_id, "pid": os.getpid(),
                     "wall_time": _round(time.time())})

    # -- internals ---------------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._tid_lock:
            tid = self._tid_map.get(ident)
            if tid is None:
                tid = self._tid_map[ident] = len(self._tid_map)
            return tid

    @staticmethod
    def _launches() -> float:
        return metrics_mod.registry().counter("device_launches").total()

    def _write(self, rec: dict) -> None:
        from fairify_tpu.resilience.journal import write_line

        line = json.dumps(rec) + "\n"
        with self._write_lock:
            if self._closed:
                return
            # Shared single-write append helper (resilience.journal): one
            # OS write per record, so a crash can tear at most the final
            # line.  No fsync here — spans are dense and advisory; the
            # verdict ledger (which fsyncs) is the record of truth.
            write_line(self._fp, line, fsync=False)

    # -- public API --------------------------------------------------------
    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def event(self, name: str, **attrs) -> None:
        rec = {"type": "event", "name": name, "ts": _round(time.time()),
               "tid": self._tid(), "attrs": attrs}
        ctx = current_context()
        if ctx is not None:
            rec["trace_id"] = ctx.trace_id
        self._write(rec)

    def close(self, snapshot_metrics: bool = True) -> None:
        if self._closed:
            return
        if snapshot_metrics:
            delta = metrics_mod.snapshot_delta(
                self._metrics0, metrics_mod.registry().snapshot())
            self._write({"type": "metrics", "ts": _round(time.time()),
                         "metrics": delta})
        with self._write_lock:
            self._closed = True
            self._fp.close()


# ---------------------------------------------------------------------------
# Active-tracer plumbing (module-level; instrumented code calls these)
# ---------------------------------------------------------------------------

_active: Optional[Tracer] = None
_active_lock = threading.Lock()


def activate(tracer: Tracer) -> None:
    global _active
    with _active_lock:
        _active = tracer


def deactivate() -> None:
    global _active
    with _active_lock:
        _active = None


def current() -> Optional[Tracer]:
    return _active


def span(name: str, **attrs):
    """A span on the active tracer, or the shared no-op when tracing is off."""
    tr = _active
    if tr is None:
        return NULL_SPAN
    return tr.span(name, **attrs)


def event(name: str, **attrs) -> None:
    tr = _active
    if tr is not None:
        tr.event(name, **attrs)


class _TracingScope:
    """Context manager behind :func:`tracing` / :func:`maybe_tracing`."""

    def __init__(self, path: Optional[str], run_id: Optional[str]):
        self._path = path
        self._run_id = run_id
        self._tracer: Optional[Tracer] = None

    def __enter__(self) -> Optional[Tracer]:
        if not self._path or current() is not None:
            # Tracing off, or an outer scope (e.g. the CLI) already owns the
            # tracer — nested sweeps must not re-open/re-export it.
            return current()
        self._tracer = Tracer(self._path, run_id=self._run_id)
        activate(self._tracer)
        return self._tracer

    def __exit__(self, *exc):
        if self._tracer is None:
            return False
        deactivate()
        self._tracer.close()
        try:
            write_chrome_trace(self._path, chrome_trace_path(self._path))
        except (OSError, ValueError):
            pass  # the event log is the record of truth; the view is best-effort
        return False


def tracing(path: Optional[str], run_id: Optional[str] = None) -> _TracingScope:
    """Own a tracer for the scope: open + activate, close + Chrome-export.

    No-op when ``path`` is falsy or a tracer is already active (so per-model
    scopes nest cleanly under a CLI-level ``--trace-out`` scope).
    """
    return _TracingScope(path, run_id)


maybe_tracing = tracing


# ---------------------------------------------------------------------------
# Readers / exporters
# ---------------------------------------------------------------------------


def load_events(path: str, count_skipped: bool = False):
    """Event-log records; tolerates truncated/partially-written lines.

    A crash mid-sweep leaves a torn final line (the same convention as the
    verdict ledger), and a crash mid-``write`` on a network filesystem can
    tear earlier lines too.  Unparseable lines are skipped, never raised on
    — but they are *counted*, so consumers (``fairify_tpu report``) can
    surface "N torn line(s) skipped" instead of silently under-reporting.

    Returns the record list, or ``(records, skipped)`` when
    ``count_skipped`` is true.  Blank lines are not torn records.
    """
    out = []
    skipped = 0
    with open(path) as fp:
        for line in fp:
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                skipped += 1
    if count_skipped:
        return out, skipped
    return out


def chrome_trace_path(jsonl_path: str) -> str:
    base = jsonl_path[:-len(".jsonl")] if jsonl_path.endswith(".jsonl") \
        else jsonl_path
    return base + ".chrome.json"


def shard_path(trace_dir: str) -> str:
    """This process's trace shard inside a shared ``--trace-dir``.

    Per-process shards are how the fleet traces without cross-process
    file locking: each process appends to its own ``trace.<pid>.jsonl``
    (crash-safe single-write appends), and the merge joins them on
    ``trace_id`` after the fact."""
    os.makedirs(trace_dir, exist_ok=True)
    return os.path.join(trace_dir, f"trace.{os.getpid()}.jsonl")


def shard_paths(trace_dir: str) -> List[str]:
    """Every trace shard under ``trace_dir`` (sorted; [] when none)."""
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return []
    return [os.path.join(trace_dir, n) for n in names
            if n.startswith("trace.") and n.endswith(".jsonl")]


def _chrome_events(records: list, pid: int, ts0: float,
                   include_instants: bool) -> list:
    out = []
    for r in records:
        args = dict(r.get("attrs") or {})
        if r.get("trace_id"):
            args["trace_id"] = r["trace_id"]
        if r.get("type") == "span":
            out.append({
                "name": r["name"], "ph": "X", "pid": pid,
                "tid": r.get("tid", 0),
                "ts": _round((r["ts"] - ts0) * 1e6, 3),
                "dur": _round(r["dur_s"] * 1e6, 3),
                "args": args,
            })
        elif r.get("type") == "event" and include_instants:
            out.append({
                "name": r["name"], "ph": "i", "s": "t", "pid": pid,
                "tid": r.get("tid", 0),
                "ts": _round((r["ts"] - ts0) * 1e6, 3),
                "args": args,
            })
    return out


def _shard_meta(records: list, fallback_pid: int) -> dict:
    meta = next((r for r in records if r.get("type") == "meta"), {})
    pid = meta.get("pid")
    return {"pid": int(pid) if pid else fallback_pid,
            "run_id": meta.get("run_id")}


def write_chrome_trace(jsonl_path: str, out_path: str,
                       include_instants: bool = True) -> int:
    """Convert one event log to Chrome ``traceEvents`` JSON (Perfetto-ready).

    Timestamps are rebased to the log's earliest record so the viewer opens
    at t=0.  Returns the number of trace events written.
    """
    records = load_events(jsonl_path)
    ts0 = min((r["ts"] for r in records if "ts" in r), default=0.0)
    meta = _shard_meta(records, fallback_pid=0)
    trace = [{"name": "process_name", "ph": "M", "pid": meta["pid"],
              "args": {"name": meta["run_id"] or "fairify_tpu"}}]
    trace += _chrome_events(records, meta["pid"], ts0, include_instants)
    with open(out_path, "w") as fp:
        json.dump({"traceEvents": trace, "displayTimeUnit": "ms"}, fp)
    return len(trace) - 1


def write_chrome_trace_merged(shard_jsonl_paths: List[str], out_path: str,
                              include_instants: bool = True) -> int:
    """Merge per-process trace shards into one Perfetto export.

    Each shard becomes its own process track, named from the shard's meta
    record (``run_id [pid N]``) and pid-namespaced so thread ids from
    different processes never collide on one track.  Timestamps are
    rebased to the earliest record across ALL shards (same-host wall
    clock), so cross-process handoffs line up visually.  Returns the
    number of (non-metadata) trace events written.
    """
    shards = []
    for i, path in enumerate(shard_jsonl_paths):
        try:
            records = load_events(path)
        except OSError:
            continue
        meta = _shard_meta(records, fallback_pid=-(i + 1))
        shards.append((path, meta, records))
    ts0 = min((r["ts"] for _p, _m, records in shards
               for r in records if "ts" in r), default=0.0)
    trace = []
    n_events = 0
    for path, meta, records in shards:
        run = meta["run_id"] or os.path.basename(path)
        trace.append({"name": "process_name", "ph": "M", "pid": meta["pid"],
                      "args": {"name": f"{run} [pid {meta['pid']}]"}})
        events = _chrome_events(records, meta["pid"], ts0, include_instants)
        n_events += len(events)
        trace += events
    with open(out_path, "w") as fp:
        json.dump({"traceEvents": trace, "displayTimeUnit": "ms"}, fp)
    return n_events

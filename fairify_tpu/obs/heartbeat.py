"""Live heartbeat: one throttled stderr line per interval during long sweeps.

The stress/relaxed grids run for minutes to hours with no output between
the stage-0 JSON lines; operators had to tail ledger files to see whether
a sweep was alive.  The heartbeat prints a single line at most once per
``interval_s``::

    [hb GC-1] 1536/3360 attempted (45.7%) | 1510 decided, 12 unknown | 24.1 pps | +38 launches | eta 79s

Throttling is clock-based (no output when the interval has not elapsed),
so per-partition call sites can beat unconditionally.  The launch delta
comes from the ``device_launches`` counter; ETA extrapolates the measured
attempt rate over the remaining partitions.  This module is the obs
layer's sanctioned progress ``print`` (see ``scripts/lint_obs.py``).
"""
from __future__ import annotations

import sys
import time
from typing import Callable, Optional

from fairify_tpu.obs import metrics as metrics_mod


class Heartbeat:
    """Throttled progress reporter; ``interval_s <= 0`` disables it."""

    def __init__(self, interval_s: float, total: Optional[int] = None,
                 label: str = "", stream=None,
                 clock: Callable[[], float] = time.monotonic):
        self.interval_s = float(interval_s)
        self.total = total
        self.label = label
        self.stream = stream  # None → sys.stderr at beat time (testable)
        self._clock = clock
        self._start = clock()
        self._last: Optional[float] = None
        self._last_launches = self._launches()

    @staticmethod
    def _launches() -> float:
        return metrics_mod.registry().counter("device_launches").total()

    def beat(self, decided: int, attempted: int, unknown: int = 0,
             force: bool = False) -> bool:
        """Emit one line if the interval elapsed (or ``force``); else no-op.

        Returns whether a line was emitted.
        """
        if self.interval_s <= 0 and not force:
            return False
        now = self._clock()
        if not force and self._last is not None \
                and now - self._last < self.interval_s:
            return False
        elapsed = max(now - self._start, 1e-9)
        pps = decided / elapsed
        launches = self._launches()
        d_launch = int(launches - self._last_launches)
        parts = [f"[hb{' ' + self.label if self.label else ''}]"]
        if self.total:
            parts.append(f"{attempted}/{self.total} attempted "
                         f"({100.0 * attempted / self.total:.1f}%)")
        else:
            parts.append(f"{attempted} attempted")
        parts.append(f"| {decided} decided, {unknown} unknown")
        parts.append(f"| {pps:.2f} pps")
        parts.append(f"| +{d_launch} launches")
        if self.total and attempted and attempted < self.total:
            rate = attempted / elapsed
            parts.append(f"| eta {(self.total - attempted) / rate:.0f}s")
        print(" ".join(parts), file=self.stream or sys.stderr, flush=True)
        self._last = now
        self._last_launches = launches
        return True

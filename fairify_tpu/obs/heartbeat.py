"""Live heartbeat: one throttled stderr line per interval during long sweeps.

The stress/relaxed grids run for minutes to hours with no output between
the stage-0 JSON lines; operators had to tail ledger files to see whether
a sweep was alive.  The heartbeat prints a single line at most once per
``interval_s``::

    [hb GC-1] 1536/3360 attempted (45.7%) | 1510 decided, 12 unknown | 24.1 pps | +38 launches | eta 79s

with ``| retries=N degraded=M`` appended whenever the run has spent launch
retries or degraded chunks (``resilience/``) — zero-noise when healthy.

Throttling is clock-based (no output when the interval has not elapsed),
so per-partition call sites can beat unconditionally.  The launch delta
comes from the ``device_launches`` counter; ETA extrapolates a RECENT
attempt rate (EMA over the last emitted beats) over the remaining
partitions — the whole-run mean lies by design on budgeted sweeps, where
the stage-0 burst (thousands of partitions per launch) is followed by the
BaB tail (seconds per partition): a mean-based ETA then promises minutes
while hours remain.  This module is the obs layer's sanctioned progress
``print`` (the ``obs-print`` lint rule allowlists it).
"""
from __future__ import annotations

import sys
import time
from typing import Callable, Optional

from fairify_tpu.obs import metrics as metrics_mod

# The run's live heartbeat (last enabled one wins; sequential sweeps each
# register their own).  obs.compile uses it to flag in-progress XLA
# compiles — the long silent pauses that otherwise look like hangs.
_ACTIVE: Optional["Heartbeat"] = None


def active() -> Optional["Heartbeat"]:
    return _ACTIVE


def notify_compile(kernel: str) -> None:
    """Flag an XLA compile start on the active heartbeat (no-op when none).

    Called by ``obs.compile`` immediately before trace/lower/compile: a cold
    stage-0 kernel compiles for tens of seconds on the tunnelled link, during
    which the partition loop — and therefore ``beat`` — cannot run, so the
    flag must be prospective.
    """
    hb = _ACTIVE
    if hb is not None:
        hb.compile_started(kernel)


class FleetPulse:
    """Throttled ``replicas alive k/N`` stderr line for replica fleets.

    The process-fleet router (``serve/procfleet.py``) beats this every
    tick; a line is printed when the live count CHANGES (a death or a
    completed restart must be visible immediately) or — while the fleet is
    degraded or restarting — at most once per ``interval_s``, so a fleet
    riding out restart backoff never looks hung.  A healthy, unchanged
    fleet prints nothing (zero-noise, like the retries/degraded suffix).
    """

    def __init__(self, interval_s: float = 5.0, label: str = "fleet",
                 stream=None, clock: Callable[[], float] = time.monotonic):
        self.interval_s = float(interval_s)
        self.label = label
        self.stream = stream  # None → sys.stderr at pulse time (testable)
        self._clock = clock
        self._last: Optional[float] = None
        self._last_alive: Optional[int] = None

    def pulse(self, alive: int, total: int, restarting: int = 0,
              rehomed: int = 0, force: bool = False) -> bool:
        """Emit one line if warranted (see class docstring); returns
        whether a line was printed."""
        if self.interval_s <= 0 and not force:
            return False
        now = self._clock()
        changed = self._last_alive is not None and alive != self._last_alive
        degraded = alive < total or restarting > 0
        throttled = self._last is not None \
            and now - self._last < self.interval_s
        if not force and not changed and (not degraded or throttled):
            self._last_alive = alive
            return False
        parts = [f"[hb {self.label}] replicas alive {alive}/{total}"]
        if restarting:
            parts.append(f"| {restarting} restarting")
        if rehomed:
            parts.append(f"| {rehomed} re-homed")
        print(" ".join(parts), file=self.stream or sys.stderr, flush=True)
        self._last = now
        self._last_alive = alive
        return True


class Heartbeat:
    """Throttled progress reporter; ``interval_s <= 0`` disables it."""

    # Recent-rate EMA weight for the ETA: one beat-to-beat window carries
    # this much, history the rest — after a phase transition (stage-0 →
    # BaB) the ETA converges to the new rate within a few beats.
    ETA_ALPHA = 0.5

    def __init__(self, interval_s: float, total: Optional[int] = None,
                 label: str = "", stream=None,
                 clock: Callable[[], float] = time.monotonic):
        self.interval_s = float(interval_s)
        self.total = total
        self.label = label
        self.stream = stream  # None → sys.stderr at beat time (testable)
        self._clock = clock
        self._start = clock()
        self._last: Optional[float] = None
        self._last_launches = self._launches()
        # Baselines for the retries/degraded suffix: the registry is
        # process-cumulative, and an earlier model's faults must not
        # flag a later (healthy) model's heartbeat as flaky.
        reg = metrics_mod.registry()
        self._retries0 = reg.counter("launch_retries").total()
        self._degraded0 = reg.counter("chunks_degraded").total()
        # Funnel baselines: the beat's decided k/N (f%) segment reads the
        # mirrored ``funnel_states`` counter (obs.funnel), which is
        # process-cumulative like the fault counters above.
        self._funnel0_total = reg.counter("funnel_states").total()
        self._funnel0_decided = self._funnel_decided()
        self._last_attempted: Optional[int] = None
        self._last_segment: Optional[float] = None
        self._rate_ema: Optional[float] = None
        if self.interval_s > 0:
            global _ACTIVE
            _ACTIVE = self

    def close(self) -> None:
        """Deregister as the live heartbeat (end of the owning sweep)."""
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None

    @staticmethod
    def _launches() -> float:
        return metrics_mod.registry().counter("device_launches").total()

    @staticmethod
    def _funnel_decided() -> int:
        from fairify_tpu.obs import funnel as funnel_mod

        return funnel_mod.live_decided()

    def compile_started(self, kernel: str) -> None:
        """One line flagging an XLA compile in progress.

        Unthrottled: a kernel compiles once per signature, so a cold run
        emits a handful of these, and each one explains a pause the
        interval-throttled beats cannot cover (the loop is blocked inside
        the compile).  Does not count as a beat for throttling.
        """
        label = f" {self.label}" if self.label else ""
        try:
            print(f"[hb{label}] compiling {kernel}…",
                  file=self.stream or sys.stderr, flush=True)
        except (OSError, ValueError):
            # A leaked/stale registration over a closed stream must never
            # fail the kernel call that triggered the flag.
            self.close()

    def segment(self, phase: str, done: int, total: int,
                in_flight: int = 0, force: bool = False) -> bool:
        """Segment-granular progress for device-resident mega launches.

        A mega segment is ONE device launch covering many chunks:
        partitions decided inside it are invisible to the host until the
        launch drains, so the per-partition ``beat`` stalls for the whole
        launch and a long single launch would look hung.  This line
        surfaces segments-done/total instead::

            [hb GC-1] stage0_decide segments 3/8 (2 in flight) | +3 launches

        Same interval throttle as ``beat`` but on its own clock (the two
        progress streams must not starve each other); the final segment of
        a phase always prints.
        """
        if self.interval_s <= 0 and not force:
            return False
        now = self._clock()
        if not force and done < total and self._last_segment is not None \
                and now - self._last_segment < self.interval_s:
            return False
        launches = self._launches()
        d_launch = int(launches - self._last_launches)
        label = f" {self.label}" if self.label else ""
        flight = f" ({in_flight} in flight)" if in_flight else ""
        print(f"[hb{label}] {phase} segments {done}/{total}{flight} "
              f"| +{d_launch} launches",
              file=self.stream or sys.stderr, flush=True)
        self._last_segment = now
        self._last_launches = launches
        return True

    def beat(self, decided: int, attempted: int, unknown: int = 0,
             force: bool = False) -> bool:
        """Emit one line if the interval elapsed (or ``force``); else no-op.

        Returns whether a line was emitted.
        """
        if self.interval_s <= 0 and not force:
            return False
        now = self._clock()
        if not force and self._last is not None \
                and now - self._last < self.interval_s:
            return False
        elapsed = max(now - self._start, 1e-9)
        pps = decided / elapsed
        launches = self._launches()
        d_launch = int(launches - self._last_launches)
        parts = [f"[hb{' ' + self.label if self.label else ''}]"]
        if self.total:
            parts.append(f"{attempted}/{self.total} attempted "
                         f"({100.0 * attempted / self.total:.1f}%)")
        else:
            parts.append(f"{attempted} attempted")
        reg = metrics_mod.registry()
        # Live funnel segment (obs.funnel): once partitions start reaching
        # terminal states the mirrored ``funnel_states`` counter drives the
        # decided line — k/N over CLASSIFIED partitions with the decided
        # fraction, the run's success metric.  Before any classification
        # (stage-0 still in flight) the caller-passed counts stand in.
        f_total = int(reg.counter("funnel_states").total()
                      - self._funnel0_total)
        if f_total > 0:
            f_dec = self._funnel_decided() - self._funnel0_decided
            parts.append(f"| decided {f_dec}/{f_total} "
                         f"({100.0 * f_dec / f_total:.1f}%), "
                         f"{f_total - f_dec} unknown")
        else:
            parts.append(f"| {decided} decided, {unknown} unknown")
        parts.append(f"| {pps:.2f} pps")
        parts.append(f"| +{d_launch} launches")
        retries = int(reg.counter("launch_retries").total() - self._retries0)
        degr = int(reg.counter("chunks_degraded").total() - self._degraded0)
        if retries or degr:
            # Fault visibility (resilience/): a flaky device shows up here
            # beats before anything degrades; omitted entirely when healthy.
            parts.append(f"| retries={retries} degraded={degr}")
        smt_workers = reg.gauge("smt_pool_workers").value()
        if smt_workers:
            # SMT pool visibility (fairify_tpu/smt): host solving in
            # flight/queued and the live worker count — omitted entirely
            # when no pool is running (zero-noise like the fault suffix).
            active = int(reg.gauge("smt_pool_active").value() or 0)
            queued = int(reg.gauge("smt_pool_queue_depth").value() or 0)
            parts.append(f"| smt: {active}/{queued} "
                         f"workers={int(smt_workers)}")
        if self._last is not None and now > self._last:
            # Fold this beat's window into the recent-rate EMA (the first
            # beat has no window → whole-run-mean fallback below).
            inst = max(attempted - (self._last_attempted or 0), 0) \
                / (now - self._last)
            self._rate_ema = inst if self._rate_ema is None else (
                self.ETA_ALPHA * inst + (1.0 - self.ETA_ALPHA) * self._rate_ema)
        if self.total and attempted and attempted < self.total:
            rate = self._rate_ema if self._rate_ema is not None \
                else attempted / elapsed
            if rate > 0:
                parts.append(f"| eta {(self.total - attempted) / rate:.0f}s")
        print(" ".join(parts), file=self.stream or sys.stderr, flush=True)
        self._last = now
        self._last_attempted = attempted
        self._last_launches = launches
        return True

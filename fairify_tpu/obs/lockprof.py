"""Opt-in dynamic lock-acquisition profiler — the static auditor's
cross-check.

:mod:`fairify_tpu.analysis.locks` claims a whole-program lock graph; a
static claim is only as good as its blind spots.  This module records the
acquisition-order edges that *actually happen* at runtime and checks them
against the static graph:

* **observed ⊆ static** — every runtime edge between locks constructed
  in ``fairify_tpu/`` must exist in the static graph.  An unmodeled edge
  is a bug in the ANALYSIS (a resolution the lexical pass missed), gated
  in CI (``tests/test_lockprof.py``) and in the chaos matrix's
  ``--lockprof`` cell.
* **cycle escalation** — a static ``lock-order`` cycle whose every edge
  manifests dynamically is not a might-be: :func:`check_against_static`
  reports it as ``confirmed_cycles`` and the callers treat that as a
  hard failure.

Mechanics: :func:`install` replaces ``threading.Lock`` / ``RLock`` /
``Condition`` with recording wrappers.  Each wrapped lock is named by its
*construction site* — the first stack frame outside this module and the
``threading`` module — which maps onto the static analysis'
``catalog()`` keyed by ``(repo-relative file, line)``.  A Condition
wrapping an already-profiled lock records through that lock's site, so
``self._cv = threading.Condition(self._lock)`` aliases exactly as the
static graph's canonical nodes do.  Per-thread held stacks turn each
successful acquire into edges from every lock the thread already holds;
``Condition.wait`` releases and re-acquires through the tracking, so the
held stack stays truthful across waits.

Strictly opt-in: nothing here runs unless :func:`install` is called (the
chaos matrix's ``--lockprof`` flag, the lockprof tests).  Locks created
*before* install (module-level registries) are simply not profiled —
the subset check covers whatever was.  Recording is in-memory;
:func:`flush_events` writes the accumulated edges to the obs event log
(``lock_edge`` events, rendered by ``fairify_tpu report``) — deferred so
the profiler never performs I/O while user code holds a lock.
"""
from __future__ import annotations

import os
import sys
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

Site = Tuple[str, int]  # (repo-relative path or abs path, line)

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_THREADING_FILE = getattr(threading, "__file__", "<threading>")


class _State:
    def __init__(self):
        self.reg_lock = _REAL_LOCK()
        self.edges: Dict[Tuple[Site, Site], int] = {}
        self.acquisitions = 0
        self.tls = threading.local()
        self.flushed: Dict[Tuple[Site, Site], int] = {}  # counts emitted


_state: Optional[_State] = None


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _caller_site() -> Site:
    """(file, line) of the frame that constructed the lock: first frame
    outside this module and threading.py, repo-relativized when inside
    the checkout so sites line up with the static catalog."""
    here = os.path.abspath(__file__)
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != here and fn != _THREADING_FILE:
            break
        f = f.f_back
    if f is None:  # pragma: no cover - interpreter internals
        return ("<unknown>", 0)
    path = f.f_code.co_filename
    root = _repo_root()
    ap = os.path.abspath(path)
    if ap.startswith(root + os.sep):
        path = os.path.relpath(ap, root).replace(os.sep, "/")
    return (path, f.f_lineno)


def _held() -> List[Site]:
    st = _state
    if st is None:  # uninstalled mid-flight: keep a per-call stack
        return []
    h = getattr(st.tls, "held", None)
    if h is None:
        h = st.tls.held = []
    return h


def _note_acquire(site: Site) -> None:
    st = _state
    if st is None:
        return
    held = _held()
    new_edges = []
    for h in held:
        if h != site:
            new_edges.append((h, site))
    held.append(site)
    with st.reg_lock:
        st.acquisitions += 1
        for e in new_edges:
            st.edges[e] = st.edges.get(e, 0) + 1


def _note_release(site: Site) -> None:
    st = _state
    if st is None:
        return
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == site:
            del held[i]
            return


class _ProfiledLock:
    """Recording proxy over a raw Lock/RLock.  Implements the private
    Condition interop hooks (``_release_save``/``_acquire_restore``/
    ``_is_owned``) so ``threading.Condition(profiled_lock)`` waits keep
    the held stack truthful — and ``_is_owned`` probes never record."""

    __slots__ = ("_inner", "site")

    def __init__(self, inner, site: Site):
        self._inner = inner
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquire(self.site)
        return ok

    def release(self) -> None:
        _note_release(self.site)
        self._inner.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return bool(locked()) if locked is not None else False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    # -- Condition interop -------------------------------------------------

    def _release_save(self):
        _note_release(self.site)
        inner_save = getattr(self._inner, "_release_save", None)
        if inner_save is not None:
            return inner_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        inner_restore = getattr(self._inner, "_acquire_restore", None)
        if inner_restore is not None:
            inner_restore(state)
        else:
            self._inner.acquire()
        _note_acquire(self.site)

    def _is_owned(self) -> bool:
        inner_owned = getattr(self._inner, "_is_owned", None)
        if inner_owned is not None:
            return bool(inner_owned())
        # Probe without recording (the default Condition probe would
        # otherwise log a spurious acquire on an unheld lock).
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<ProfiledLock {self.site[0]}:{self.site[1]}>"


def _profiled_lock():
    return _ProfiledLock(_REAL_LOCK(), _caller_site())


def _profiled_rlock():
    return _ProfiledLock(_REAL_RLOCK(), _caller_site())


class _ProfiledCondition(_REAL_CONDITION):
    """Condition whose default (internal) lock is profiled too.

    With an explicit profiled lock the base class already routes
    acquire/release/wait through the proxy's hooks — recording happens at
    the LOCK's construction site, which is exactly the static graph's
    canonical node for an aliasing ``Condition(self._lock)``."""

    def __init__(self, lock=None):
        if lock is None:
            lock = _ProfiledLock(_REAL_RLOCK(), _caller_site())
        super().__init__(lock)


def install() -> None:
    """Start profiling (idempotent).  Locks constructed AFTER this call
    record; pre-existing locks are invisible (and excluded from checks)."""
    global _state
    if _state is not None:
        return
    _state = _State()
    threading.Lock = _profiled_lock
    threading.RLock = _profiled_rlock
    threading.Condition = _ProfiledCondition


def uninstall() -> None:
    """Stop profiling and restore threading's factories.  Already-created
    proxies keep working (recording stops — ``_state`` is gone)."""
    global _state
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _state = None


def installed() -> bool:
    return _state is not None


def reset() -> None:
    st = _state
    if st is not None:
        with st.reg_lock:
            st.edges.clear()
            st.flushed.clear()
            st.acquisitions = 0


def observed_edges() -> Dict[Tuple[Site, Site], int]:
    st = _state
    if st is None:
        return {}
    with st.reg_lock:
        return dict(st.edges)


def flush_events() -> int:
    """Emit one ``lock_edge`` obs event per edge whose count GREW since
    the last flush (incremental: events carry the delta, so a periodic
    flusher's report sums stay exact).  Deferred from acquire time so
    profiling never does I/O under a user lock."""
    from fairify_tpu.obs import trace as trace_mod

    st = _state
    if st is None:
        return 0
    with st.reg_lock:
        pending = []
        for e, n in sorted(st.edges.items()):
            delta = n - st.flushed.get(e, 0)
            if delta > 0:
                pending.append((e, delta))
                st.flushed[e] = n
    for (src, dst), delta in pending:
        trace_mod.event("lock_edge", src=f"{src[0]}:{src[1]}",
                        dst=f"{dst[0]}:{dst[1]}", count=delta)
    return len(pending)


# ---------------------------------------------------------------------------
# The cross-check
# ---------------------------------------------------------------------------


@dataclass
class LockprofReport:
    """Outcome of one observed-vs-static comparison."""

    observed: int = 0             # edges recorded (all)
    in_scope: int = 0             # edges with both ends in the catalog
    external: int = 0             # edges with an end outside fairify_tpu/
    unmodeled: List[str] = field(default_factory=list)   # NOT in the graph
    confirmed_cycles: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.unmodeled and not self.confirmed_cycles

    def as_dict(self) -> dict:
        return {"observed": self.observed, "in_scope": self.in_scope,
                "external": self.external, "unmodeled": list(self.unmodeled),
                "confirmed_cycles": list(self.confirmed_cycles),
                "ok": self.ok}


def check_against_static(analysis=None, root: Optional[str] = None,
                         edges: Optional[Dict[Tuple[Site, Site], int]] = None
                         ) -> LockprofReport:
    """Observed edges vs the static graph (see module docstring).

    ``analysis`` overrides the repo-wide build (tests check toy modules);
    ``edges`` overrides the live profiler state.  ``unmodeled`` entries
    are the CI-failing condition: a runtime edge between two catalogued
    fairify locks that the static graph lacks.  ``confirmed_cycles`` are
    static lock-order cycles whose every edge manifested — escalated to
    a hard failure by every caller.
    """
    from fairify_tpu.analysis.locks import build_repo_analysis

    if analysis is None:
        analysis = build_repo_analysis(root)
    catalog = analysis.catalog()
    static = set(analysis.edges)
    got = observed_edges() if edges is None else edges
    rep = LockprofReport(observed=len(got))
    seen_canonical = set()
    for (src, dst), _n in sorted(got.items()):
        a, b = catalog.get(src), catalog.get(dst)
        if a is None or b is None:
            rep.external += 1
            continue
        rep.in_scope += 1
        if a == b:
            continue  # aliased cv/lock pair or re-entrant acquire
        seen_canonical.add((a, b))
        if (a, b) not in static:
            rep.unmodeled.append(
                f"{a} -> {b} (observed {src[0]}:{src[1]} -> "
                f"{dst[0]}:{dst[1]})")
    for cycle in analysis.cycles():
        if all((s, d) in seen_canonical for s, d, _w in cycle):
            rep.confirmed_cycles.append(
                " -> ".join([s for s, _d, _w in cycle]
                            + [cycle[0][0]]))
    return rep

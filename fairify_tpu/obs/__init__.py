"""Observability layer: span tracing, metrics registry, heartbeat, report.

The verification pipeline's throughput is governed by device-launch economy
and per-phase wall time (each launch costs ~110 ms flat on the tunnelled
single-chip setup — audits/device_util_r4.json); this package makes both
first-class instead of ad-hoc:

* :mod:`fairify_tpu.obs.trace` — nested, thread-safe spans appended to a
  per-run JSONL event log, exportable as a Chrome trace
  (``chrome://tracing`` / Perfetto).  Disabled by default; the off path is
  one global read per span.
* :mod:`fairify_tpu.obs.metrics` — named counters / gauges / histograms
  with labels, resettable per run (absorbs the old module-global
  ``_LAUNCHES`` and the ``ThroughputCounter`` fields).
* :mod:`fairify_tpu.obs.heartbeat` — a throttled stderr progress line for
  long sweeps (flags in-progress XLA compiles).
* :mod:`fairify_tpu.obs.compile` — :func:`obs_jit`, the ``jax.jit`` drop-in
  behind every verify/ and ops/ device kernel: a stable-name kernel
  registry with compile spans, recompile accounting, and first-compile
  cost/memory analysis.
* :mod:`fairify_tpu.obs.report` — aggregates event logs into phase /
  verdict / launch breakdown tables (the ``fairify_tpu report``
  subcommand).

Instrumented code imports this package only (``from fairify_tpu import
obs``) and uses :func:`obs.span` / :func:`obs.timed_span` /
:func:`obs.event` / :func:`obs.registry`; tracers are owned by entry
points via :func:`obs.tracing`.
"""
from __future__ import annotations

import contextlib

from fairify_tpu.obs.heartbeat import Heartbeat  # noqa: F401
from fairify_tpu.obs.metrics import MetricsRegistry, registry  # noqa: F401
from fairify_tpu.obs.trace import (  # noqa: F401
    TraceContext,
    Tracer,
    chrome_trace_path,
    context,
    context_fields,
    current,
    current_context,
    event,
    load_events,
    maybe_tracing,
    new_trace_id,
    shard_path,
    shard_paths,
    span,
    tracing,
    write_chrome_trace,
    write_chrome_trace_merged,
)


def __getattr__(name):
    # Lazy: obs.compile imports jax at module load, but the report/trace
    # consumers of this package (``fairify_tpu report`` aggregates logs
    # host-side) must stay importable without paying — or depending on —
    # a jax import.  Kernel modules reach obs_jit through this hook (or
    # import fairify_tpu.obs.compile directly); they import jax anyway.
    if name == "obs_jit":
        from fairify_tpu.obs.compile import obs_jit

        return obs_jit
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


@contextlib.contextmanager
def timed_span(timer, name: str, **attrs):
    """A span that also accumulates into a :class:`PhaseTimer` phase.

    The sweep's budget math (hard-timeout enforcement, per-row amortized
    stage-0 share) runs off ``PhaseTimer`` totals whether or not tracing is
    enabled; this keeps that always-on accounting and the optional event
    log in one instrumentation point.

    While an XLA profiler capture is open (``--xprof-dir`` →
    ``utils.profiling.xla_trace``) the phase also stamps the device
    timeline with a ``TraceAnnotation`` of the SAME name, so the XProf
    view and the Perfetto span view join on shared phase names; untraced
    runs pay one integer check.
    """
    from fairify_tpu.utils import profiling as _profiling

    with _profiling.annotation(name), span(name, **attrs) as sp:
        if timer is None:
            yield sp
        else:
            with timer.phase(name):
                yield sp

"""Aggregate run event logs into phase / verdict / launch breakdowns.

Powers the ``fairify_tpu report`` subcommand: given one or more ``--trace-out``
JSONL logs (a single run, a multi-host run's per-host logs, or a whole
results directory's worth), produce

* a **phase table** — per span name: count, total seconds, device-launch
  attribution (spans nest, so a parent's totals include its children —
  the table is a breakdown by instrumentation point, not a partition of
  wall time);
* a **verdict table** — per model: sat / unsat / unknown, decided-vs-
  attempted, split by the deciding stage (the per-partition ``verdict``
  events the sweep emits carry a ``via`` attr);
* a **per-kernel compile table** — per ``obs_jit`` kernel: compiles,
  distinct signatures, total compile seconds, first-compile FLOPs and
  temp-buffer bytes (from the ``compile.<kernel>`` spans, backfilled from
  the closing metrics snapshot for compiles that predate tracer
  activation);
* the run's **device-launch total** (from the closing metrics snapshot);
* a **degradation table** — fault-degraded UNKNOWN partitions bucketed by
  machine-readable reason code (``site:kind``), read from degraded verdict
  events or directly from verdict-ledger files (``*.ledger.jsonl`` may be
  passed as inputs; their ``failure`` records are the source of truth);
* an **integrity table** — the result-integrity layer's counters
  (DESIGN.md §21) from each run's closing metrics snapshot: detected
  corruption by injection site (``integrity_violations``), sampled
  rechecks by kind (``integrity_rechecks``), checksum-rejected ledger
  rows (``ledger_crc_mismatch``) and suspect-replica flags
  (``replica_suspect``) — every value here is a corruption that was
  CAUGHT; a healthy run renders an all-zero (absent) table;
* an **SMT outcome table** — per-reason query outcomes of the worker pool
  (``fairify_tpu/smt``): decided vs ``timeout`` / ``memout`` /
  ``solver-error`` / ``smt.worker:*`` worker-death reasons, read from the
  ``smt_queries`` counter series of each run's closing metrics snapshot —
  next to the degradation table so host-solver health reads at a glance;
* a **per-shard table** — for sharded sweeps (``parallel.shards``; span-
  qualified sinks ``model@start-stop`` or ``failure`` records carrying a
  ``shard`` index): per shard, verdict counts and how many partitions
  degraded — the shard-loss blast radius at a glance;
* a **request table** — for service runs (``fairify_tpu serve``; the
  server journals every lifecycle transition as a ``request`` event):
  per request, final status, queue wait, run seconds and whether its SLA
  was missed, last-transition-wins per request id (the event stream
  replays a request's whole lifecycle; the terminal record is truth);
* with ``--trace-dir``, a **per-request critical-path table** joined
  across the fleet's per-process trace shards on ``trace_id`` (DESIGN.md
  §19): queue wait, admission, batch-coalesce, compile, device, SMT and
  drain seconds per request, plus which replica (and which SMT worker
  pids) served it — and one merged Perfetto export
  (``<dir>/merged.chrome.json``) with pid-namespaced process tracks.

Torn/partially-written lines (crash mid-sweep) are skipped with a counted
warning, never raised on.

The same aggregate is emitted as JSON (``--json-out`` / ``--json``) so
BENCH/PERF tooling can consume it without re-parsing tables.
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, List

from fairify_tpu.obs import trace as trace_mod


def _ledger_stem(path: str) -> str:
    """Model label for a verdict-ledger file passed directly to the report."""
    import os

    base = os.path.basename(path)
    for suffix in (".ledger.jsonl", ".jsonl"):
        if base.endswith(suffix):
            return base[:-len(suffix)]
    return base


def _counter_total(metrics: dict, name: str) -> float:
    inst = metrics.get(name)
    if not inst:
        return 0.0
    return sum(s.get("value", 0) for s in inst.get("series", []))


def aggregate(paths: Iterable[str]) -> dict:
    """Merge one or more event logs into a single summary dict.

    Per-partition verdict events are deduplicated on ``(model,
    partition_id)`` with last-record-wins: a resumed run appends
    ``via="ledger"`` replays of partitions the crashed run already logged,
    and a retry run re-decides previously-unknown partitions — in both
    cases the latest record is the record of truth, and counts stay equal
    to the final ModelReport's.  (Multi-host logs have disjoint partition
    spans, so cross-file dedup never collides.)
    """
    phases: Dict[str, dict] = {}
    span_count = 0
    skipped_lines = 0
    launches = 0.0
    inflight_max = 0.0
    inflight_means: List[float] = []
    files = 0
    keyed: Dict[tuple, dict] = {}  # (model, partition_id) -> attrs, last wins
    anon: List[dict] = []  # verdict events without a partition id
    requests: Dict[str, dict] = {}  # request id -> lifecycle attrs, last wins
    replicas: Dict[int, dict] = {}  # process-fleet replica rows (`replica`)
    compiles: Dict[str, dict] = {}  # kernel -> compile-table row
    smt_outcomes: Dict[str, int] = {}  # decided / per-reason query counts
    integrity: Dict[str, Dict[str, int]] = {}  # counter -> label -> count
    lock_edges: Dict[tuple, int] = {}  # (src site, dst site) -> count
    segments: Dict[str, dict] = {}  # mega-loop phase -> done/total row
    funnel_hist = None              # summed margin/gap histogram payload
    funnel_loos: Dict[str, list] = {}  # model -> per-layer looseness sums
    funnel_event_states: Dict[str, int] = {}  # fallback when no verdicts
    for path in paths:
        files += 1
        records, skipped = trace_mod.load_events(path, count_skipped=True)
        skipped_lines += skipped
        ledger_model = _ledger_stem(path)
        for rec in records:
            rtype = rec.get("type")
            if rtype is None and "partition_id" in rec and "verdict" in rec:
                # A verdict-ledger file (``*.ledger.jsonl``) was passed
                # directly: fold its records into the verdict/degradation
                # tables under the file's model stem, same last-wins dedup
                # as verdict events.  (Pass event logs OR ledgers, not a
                # run's both — the rows would double count across stems.)
                attrs = {"model": ledger_model,
                         "partition_id": rec["partition_id"],
                         "verdict": rec["verdict"], "via": "ledger-file"}
                fail = rec.get("failure")
                if fail:
                    attrs["failure"] = fail.get("reason", "?") \
                        if isinstance(fail, dict) else str(fail)
                    if isinstance(fail, dict) and fail.get("shard") is not None:
                        attrs["shard"] = fail["shard"]
                keyed[(ledger_model, rec["partition_id"])] = attrs
                continue
            if rtype == "span":
                span_count += 1
                name = rec["name"]
                attrs = rec.get("attrs", {})
                if name.startswith("compile."):
                    row = compiles.setdefault(name[len("compile."):], {
                        "count": 0, "total_s": 0.0, "signatures": set(),
                        "flops": None, "temp_bytes": None})
                    row["count"] += 1
                    row["total_s"] += rec.get("dur_s", 0.0)
                    if attrs.get("signature") is not None:
                        row["signatures"].add(
                            (attrs.get("signature"), attrs.get("static")))
                    for k in ("flops", "temp_bytes"):
                        if row[k] is None and attrs.get(k) is not None:
                            row[k] = attrs[k]
                    continue  # compile spans live in their own table
                ph = phases.setdefault(
                    name, {"count": 0, "total_s": 0.0, "launches": 0})
                ph["count"] += 1
                ph["total_s"] += rec.get("dur_s", 0.0)
                ph["launches"] += int(attrs.get("launches", 0))
            elif rtype == "event" and rec.get("name") == "request":
                attrs = rec.get("attrs", {})
                rid = attrs.get("request")
                if rid is not None:
                    requests[rid] = attrs
            elif rtype == "event" and rec.get("name") == "replica":
                # Process-fleet lifecycle (serve.procfleet): spawn/hello/
                # death/restart/rehome events fold into one row per
                # replica slot — pid is last-wins, counters accumulate.
                attrs = rec.get("attrs", {})
                if attrs.get("replica") is None:
                    continue
                row = replicas.setdefault(int(attrs["replica"]), {
                    "pid": None, "restarts": 0, "deaths": {},
                    "rehomed": 0, "last_lease_age_s": None,
                    "abandoned": False, "exec_cache_hit_rate": None,
                    "launches_per_model": None})
                ev = attrs.get("event")
                if attrs.get("pid") is not None:
                    row["pid"] = int(attrs["pid"])
                if ev == "metrics":
                    # Live fleet telemetry (procfleet metrics beats): the
                    # router-derived gauges, last-beat-wins per slot.
                    for k in ("exec_cache_hit_rate", "launches_per_model"):
                        if attrs.get(k) is not None:
                            row[k] = attrs[k]
                elif ev == "restart":
                    row["restarts"] = max(row["restarts"],
                                          int(attrs.get("restarts", 0)))
                elif ev == "death":
                    kind = str(attrs.get("kind", "?"))
                    row["deaths"][kind] = row["deaths"].get(kind, 0) + 1
                elif ev == "rehome":
                    row["rehomed"] += int(attrs.get("requests", 0))
                elif ev == "lease_expired":
                    row["last_lease_age_s"] = attrs.get("lease_age")
                elif ev == "abandoned":
                    row["abandoned"] = True
            elif rtype == "event" and rec.get("name") == "lock_edge":
                # Dynamic lock-order edges (obs.lockprof flush): summed
                # across logs, keyed by src -> dst construction sites.
                attrs = rec.get("attrs", {})
                key = (attrs.get("src", "?"), attrs.get("dst", "?"))
                lock_edges[key] = lock_edges.get(key, 0) \
                    + int(attrs.get("count", 1))
            elif rtype == "event" and rec.get("name") == "segment":
                # Mega-loop segment progress (DESIGN.md §17): per phase,
                # the latest done/total plus how many partitions drained
                # through segment launches — the host-visible progress
                # grain while a device-resident launch is in flight.
                attrs = rec.get("attrs", {})
                row = segments.setdefault(
                    str(attrs.get("phase", "?")),
                    {"done": 0, "total": 0, "partitions": 0})
                row["done"] = int(attrs.get("done", row["done"]))
                row["total"] = int(attrs.get("total", row["total"]))
                row["partitions"] += int(attrs.get("partitions", 0))
            elif rtype == "event" and rec.get("name") == "funnel":
                # Funnel telemetry (obs.funnel, DESIGN.md §20): one event
                # per model run carrying terminal-state counts plus the
                # stage-0 margin/gap histograms and per-layer looseness.
                # Serve additionally emits a per-REQUEST event (tagged with
                # a ``request`` attr) that aggregates the same sub-runs —
                # skipped here so nothing double counts.
                attrs = rec.get("attrs", {})
                if attrs.get("request") is not None:
                    continue
                for s, n in (attrs.get("states") or {}).items():
                    funnel_event_states[s] = \
                        funnel_event_states.get(s, 0) + int(n)
                mh = attrs.get("margin_hist")
                if mh:
                    if funnel_hist is None:
                        funnel_hist = {"edges": list(mh["edges"]),
                                       "margin": [0] * len(mh["margin"]),
                                       "gap": [0] * len(mh["gap"])}
                    funnel_hist["margin"] = [
                        a + int(b) for a, b in
                        zip(funnel_hist["margin"], mh["margin"])]
                    funnel_hist["gap"] = [
                        a + int(b)
                        for a, b in zip(funnel_hist["gap"], mh["gap"])]
                lo = attrs.get("looseness")
                if lo is not None:
                    model = str(attrs.get("model", "?"))
                    prev = funnel_loos.get(model)
                    if prev is None or len(prev) != len(lo):
                        funnel_loos[model] = [float(v) for v in lo]
                    else:
                        funnel_loos[model] = [a + float(v)
                                              for a, v in zip(prev, lo)]
            elif rtype == "event" and rec.get("name") == "verdict":
                attrs = rec.get("attrs", {})
                if attrs.get("verdict") not in ("sat", "unsat", "unknown"):
                    continue
                pid = attrs.get("partition_id")
                if pid is None:
                    anon.append(attrs)
                else:
                    keyed[(attrs.get("model", "?"), pid)] = attrs
            elif rtype == "metrics":
                # Each record is a per-run delta (tracer close), so multiple
                # runs appended to one file sum correctly.
                metrics = rec.get("metrics", {})
                launches += _counter_total(metrics, "device_launches")
                # SMT pool outcomes: decided verdicts fold into one row,
                # unknowns keep their machine-readable reason (timeout /
                # memout / solver-error / smt.worker:<death>).
                for s in metrics.get("smt_queries", {}).get("series", []):
                    labels = dict(s.get("labels", {}))
                    key = "decided" if labels.get("verdict") in \
                        ("sat", "unsat") else labels.get("reason", "?")
                    smt_outcomes[key] = smt_outcomes.get(key, 0) \
                        + int(s.get("value", 0))
                # Result-integrity counters (DESIGN.md §21): violations keep
                # their detection site, rechecks their kind; the unlabeled
                # CRC / suspect counters fold under "-".  Only nonzero
                # series land here, so a clean run has no integrity block.
                for cname, lab in (("integrity_violations", "site"),
                                   ("integrity_rechecks", "kind")):
                    for s in metrics.get(cname, {}).get("series", []):
                        n = int(s.get("value", 0))
                        if not n:
                            continue
                        key = dict(s.get("labels", {})).get(lab, "?")
                        row = integrity.setdefault(cname, {})
                        row[key] = row.get(key, 0) + n
                for cname in ("ledger_crc_mismatch", "replica_suspect"):
                    tot = int(_counter_total(metrics, cname))
                    if tot:
                        row = integrity.setdefault(cname, {})
                        row["-"] = row.get("-", 0) + tot
                # Compiles that happened while no tracer was active (e.g. a
                # warm-up pass inside the traced scope's registry window)
                # have no compile.<kernel> span; the closing snapshot's
                # per-kernel counter/histogram series still carry them.
                for s in metrics.get("xla_compiles", {}).get("series", []):
                    kern = dict(s.get("labels", {})).get("kernel", "?")
                    row = compiles.setdefault(kern, {
                        "count": 0, "total_s": 0.0, "signatures": set(),
                        "flops": None, "temp_bytes": None})
                    row.setdefault("metric_count", 0)
                    row["metric_count"] += int(s.get("value", 0))
                for s in metrics.get("xla_compile_seconds",
                                     {}).get("series", []):
                    kern = dict(s.get("labels", {})).get("kernel", "?")
                    row = compiles.get(kern)
                    if row is not None:
                        row.setdefault("metric_s", 0.0)
                        row["metric_s"] += float(s.get("sum", 0.0))
                # Async-pipeline overlap gauge (labels stat=max / stat=mean,
                # last-write-wins per run): across runs, aggregate the peak
                # of the maxes and the unweighted average of per-run means
                # (per-run drain durations aren't in the snapshot, so a
                # time-weighted cross-run mean isn't reconstructible).
                for s in rec.get("metrics", {}).get(
                        "launches_in_flight", {}).get("series", []):
                    stat = dict(s.get("labels", {})).get("stat")
                    if stat == "max":
                        inflight_max = max(inflight_max, s.get("value", 0))
                    elif stat == "mean":
                        inflight_means.append(s.get("value", 0))

    models: Dict[str, dict] = {}
    verdicts = {"sat": 0, "unsat": 0, "unknown": 0}
    via: Dict[str, int] = {}
    degraded: Dict[str, int] = {}  # failure reason -> partition count
    shards: Dict[str, dict] = {}   # per-shard verdict/degradation rows
    funnel_states: Dict[str, int] = {}
    from fairify_tpu.obs import funnel as funnel_mod

    for attrs in list(keyed.values()) + anon:
        v = attrs["verdict"]
        verdicts[v] += 1
        # Terminal funnel state per (deduped) partition: the last-wins
        # dedup above means an SMT-superseded provisional UNKNOWN is
        # classified from its FINAL verdict event, which the in-run
        # FunnelCounts tally cannot do.
        state = funnel_mod.classify(
            v, str(attrs.get("via", "?")), failure=attrs.get("failure"),
            engine_reason=attrs.get("engine_reason"))
        funnel_states[state] = funnel_states.get(state, 0) + 1
        models.setdefault(attrs.get("model", "?"),
                          {"sat": 0, "unsat": 0, "unknown": 0})[v] += 1
        if v != "unknown":  # the breakdown is of DECIDED partitions
            via[attrs.get("via", "?")] = via.get(attrs.get("via", "?"), 0) + 1
        elif attrs.get("failure"):
            # Fault-degraded UNKNOWNs (ledger `failure` records / degraded
            # verdict events), bucketed by machine-readable reason code.
            r = attrs["failure"]
            degraded[r] = degraded.get(r, 0) + 1
        # Per-shard rows: span-qualified sink stems name the shard's span
        # (parallel.shards keeps one journal per initial shard); a failure
        # record's `shard` index labels losses attributed after re-shard.
        model = str(attrs.get("model", "?"))
        label = model if "@" in model else (
            f"shard {attrs['shard']}" if attrs.get("shard") is not None
            else None)
        if label is not None:
            row = shards.setdefault(label, {"sat": 0, "unsat": 0,
                                            "unknown": 0, "degraded": 0})
            row[v] += 1
            if v == "unknown" and attrs.get("failure"):
                row["degraded"] += 1
    decided = verdicts["sat"] + verdicts["unsat"]
    compile_table = {}
    for kern, row in sorted(compiles.items(),
                            key=lambda kv: -(kv[1]["total_s"]
                                             or kv[1].get("metric_s", 0.0))):
        # Spans are authoritative when present (they carry signatures and
        # durations); the metrics snapshot backfills span-less compiles.
        count = max(row["count"], row.get("metric_count", 0))
        total_s = row["total_s"] if row["count"] else row.get("metric_s", 0.0)
        compile_table[kern] = {
            "count": count,
            "total_s": round(total_s, 3),
            "signatures": len(row["signatures"]) if row["signatures"]
            else None,
            "flops": row["flops"],
            "temp_bytes": row["temp_bytes"],
        }
    request_table = {}
    for rid in sorted(requests):
        attrs = requests[rid]
        request_table[rid] = {
            "model": attrs.get("model", "?"),
            "status": attrs.get("status", "?"),
            "queue_wait_s": round(float(attrs.get("queue_wait_s", 0.0)), 4),
            "run_s": round(float(attrs.get("run_s", 0.0)), 4),
            "deadline_missed": bool(attrs.get("deadline_missed", False)),
            "decided": (int(attrs.get("sat", 0)) + int(attrs.get("unsat", 0)))
            if "sat" in attrs else None,
            "reason": attrs.get("reason"),
        }
    return {
        "files": files,
        "span_count": span_count,
        "skipped_lines": skipped_lines,
        "compiles": compile_table,
        "phases": {k: {"count": v["count"],
                       "total_s": round(v["total_s"], 3),
                       "launches": v["launches"]}
                   for k, v in sorted(phases.items(),
                                      key=lambda kv: -kv[1]["total_s"])},
        "verdicts": verdicts,
        "decided": decided,
        "attempted": decided + verdicts["unknown"],
        "via": via,
        "degraded": dict(sorted(degraded.items(), key=lambda kv: -kv[1])),
        "smt": dict(sorted(smt_outcomes.items(), key=lambda kv: -kv[1])),
        "integrity": {k: dict(sorted(integrity[k].items()))
                      for k in sorted(integrity)},
        "shards": {k: shards[k] for k in sorted(shards)},
        "requests": request_table,
        "replicas": {str(k): replicas[k] for k in sorted(replicas)},
        "lock_edges": [{"src": s, "dst": d, "count": n}
                       for (s, d), n in sorted(lock_edges.items())],
        "segments": {k: segments[k] for k in sorted(segments)},
        # Funnel block: states from the deduped verdict events when any
        # exist (they carry SMT supersession and retry re-decisions);
        # funnel-event sums cover logs with no per-partition events (e.g.
        # a budgeted ladder's unattempted ``unknown:budget`` tail).
        "funnel": {
            "states": dict(sorted((funnel_states or
                                   funnel_event_states).items())),
            "decided_fraction": round(funnel_mod.decided_fraction(
                funnel_states or funnel_event_states), 6),
            "margin_hist": funnel_hist,
            "looseness": {k: [round(v, 3) for v in funnel_loos[k]]
                          for k in sorted(funnel_loos)},
        },
        "models": models,
        "device_launches": int(launches),
        "launches_in_flight_max": int(inflight_max),
        "launches_in_flight_mean": round(
            sum(inflight_means) / len(inflight_means), 3)
        if inflight_means else 0.0,
    }


#: Span name → critical-path stage column.  ``serve.smt_drain`` is the
#: server-side wall clock of the SMT leg; ``smt.pool_query`` (same
#: process, nested) and ``smt.worker_solve`` (the worker shard) are
#: fallbacks when the outer span is absent, never added on top — the
#: three nest, and summing nested spans double-counts.
_SMT_TIERS = ("serve.smt_drain", "smt.pool_query", "smt.worker_solve")


def _stage_of(name: str):
    if name == "serve.admit":
        return "admission_s"
    if name == "serve.batch_stage0":
        # ONLY the coalesced stage-0 wave: ``serve.batch`` wraps the whole
        # batch execution (refinement included), so charging it here would
        # show a coalesce column bigger than the request's own latency.
        return "coalesce_s"
    if name.startswith("compile."):
        return "compile_s"
    if name == "pipeline.drain":
        return "drain_s"
    return None


def critical_paths(paths: Iterable[str]) -> Dict[str, dict]:
    """Per-request critical-path rows joined across per-process shards.

    The join key is ``trace_id`` — span ids are per-process counters and
    never joined on (DESIGN.md §19).  Batch spans (``serve.batch*``)
    serve several requests at once and carry a ``trace_ids`` list; their
    duration is charged to every listed request as the coalesce stage.
    ``device_s`` is the residual of the request's measured run seconds
    after the instrumented stages — the un-spanned dispatch/execute time
    — so each row's stages sum exactly to its measured latency
    (``queue_wait_s + run_s``); ``complete`` marks rows whose request
    reached a terminal status AND had spans recorded under its trace.
    """
    spans: Dict[str, list] = {}      # trace_id -> [(span rec, shard meta)]
    req_events: Dict[str, dict] = {}  # trace_id -> merged request attrs
    for i, path in enumerate(paths):
        records, _skipped = trace_mod.load_events(path, count_skipped=True)
        meta = trace_mod._shard_meta(records, fallback_pid=-(i + 1))
        for rec in records:
            rtype = rec.get("type")
            if rtype == "span":
                tid = rec.get("trace_id")
                listed = rec.get("attrs", {}).get("trace_ids")
                for t in ([tid] if tid else []) + list(listed or []):
                    spans.setdefault(t, []).append((rec, meta))
            elif rtype == "event" and rec.get("name") == "request":
                attrs = rec.get("attrs", {})
                t = rec.get("trace_id") or attrs.get("trace_id")
                if t:
                    req_events.setdefault(t, {}).update(attrs)
    rows: Dict[str, dict] = {}
    for t in sorted(set(spans) | set(req_events)):
        attrs = req_events.get(t, {})
        row = {"request": attrs.get("request"),
               "status": attrs.get("status"),
               "replica": attrs.get("replica"),
               "worker_pids": [],
               "queue_wait_s": round(float(attrs.get("queue_wait_s", 0.0)), 4),
               "run_s": round(float(attrs.get("run_s", 0.0)), 4),
               "admission_s": 0.0, "coalesce_s": 0.0, "compile_s": 0.0,
               "smt_s": 0.0, "drain_s": 0.0, "device_s": 0.0}
        # A failed-over request has spans from BOTH the killed owner's
        # torn attempt and the survivor's resume replay.  The critical
        # path is the attempt that finished: stages are charged from the
        # process whose ``serve.request`` span is latest (the terminal
        # status record's run seconds describe exactly that attempt);
        # worker solve spans join from whatever SMT worker pids served it.
        serve_pid = None
        serve_ts = None
        for rec, meta in spans.get(t, []):
            if rec.get("name") == "serve.request":
                ts = float(rec.get("ts", 0.0))
                if serve_ts is None or ts >= serve_ts:
                    serve_ts, serve_pid = ts, meta["pid"]
                    if not row["run_s"]:
                        row["run_s"] = round(float(rec.get("dur_s", 0.0)), 4)
        smt = {name: 0.0 for name in _SMT_TIERS}
        worker_pids = set()
        for rec, meta in spans.get(t, []):
            name = rec.get("name", "")
            dur = float(rec.get("dur_s", 0.0))
            if name == "smt.worker_solve":
                smt[name] += dur
                worker_pids.add(meta["pid"])
                continue
            if serve_pid is not None and meta["pid"] != serve_pid:
                continue  # the torn attempt's stages are not the path
            stage = _stage_of(name)
            if stage is not None:
                row[stage] += dur
            elif name in smt:
                smt[name] += dur
        # Outermost-present SMT tier only (they nest across processes).
        row["smt_s"] = next((smt[n] for n in _SMT_TIERS if smt[n] > 0), 0.0)
        row["worker_pids"] = sorted(worker_pids)
        if serve_pid is not None:
            row["replica_pid"] = serve_pid
        instrumented = row["compile_s"] + row["smt_s"] + row["drain_s"]
        row["device_s"] = round(max(row["run_s"] - instrumented, 0.0), 4)
        row["total_s"] = round(row["queue_wait_s"] + row["run_s"], 4)
        for k in ("admission_s", "coalesce_s", "compile_s", "smt_s",
                  "drain_s"):
            row[k] = round(row[k], 4)
        row["complete"] = bool(spans.get(t)) and row["status"] in \
            ("done", "failed", "rejected")
        rows[t] = row
    return rows


def render_critical_paths(rows: Dict[str, dict]) -> str:
    """Monospace critical-path table (one row per traced request)."""
    lines: List[str] = []
    if not rows:
        return ""
    w = max(max(len(str(r["request"] or t)[:18]) for t, r in rows.items()),
            len("request"))
    lines.append(f"{'request':<{w}}  {'replica':>7}  {'wait_s':>7}  "
                 f"{'admit':>6}  {'coalesce':>8}  {'compile':>7}  "
                 f"{'device':>7}  {'smt':>6}  {'drain':>6}  {'total':>7}")
    complete = 0
    for t, r in sorted(rows.items(), key=lambda kv: -kv[1]["total_s"]):
        complete += int(r["complete"])
        rep = r["replica"] if r["replica"] is not None else "-"
        label = str(r["request"] or t)[:18]
        mark = "" if r["complete"] else " (partial)"
        lines.append(
            f"{label:<{w}}  {rep!s:>7}  {r['queue_wait_s']:>7.3f}  "
            f"{r['admission_s']:>6.3f}  {r['coalesce_s']:>8.3f}  "
            f"{r['compile_s']:>7.3f}  {r['device_s']:>7.3f}  "
            f"{r['smt_s']:>6.3f}  {r['drain_s']:>6.3f}  "
            f"{r['total_s']:>7.3f}{mark}")
    lines.append(f"traced requests: {len(rows)}   "
                 f"complete critical paths: {complete}")
    return "\n".join(lines)


def render(agg: dict) -> str:
    """Human-readable tables for one aggregate (monospace, stdout-ready)."""
    lines: List[str] = []
    lines.append(f"event logs: {agg['files']}   spans: {agg['span_count']}   "
                 f"device launches: {agg['device_launches']}")
    if agg.get("skipped_lines"):
        lines.append(f"warning: {agg['skipped_lines']} torn/truncated "
                     f"line(s) skipped (crash mid-write)")
    if agg.get("launches_in_flight_max"):
        lines.append(f"launches in flight: max {agg['launches_in_flight_max']}"
                     f"   mean {agg['launches_in_flight_mean']:.2f}"
                     f"   (async pipeline overlap)")
    if agg["phases"]:
        w = max(len(k) for k in agg["phases"])
        lines.append("")
        lines.append(f"{'phase':<{w}}  {'count':>7}  {'total_s':>10}  {'launches':>8}")
        for name, ph in agg["phases"].items():
            lines.append(f"{name:<{w}}  {ph['count']:>7}  "
                         f"{ph['total_s']:>10.3f}  {ph['launches']:>8}")
    if agg["models"]:
        w = max(max(len(k) for k in agg["models"]), len("TOTAL"))
        lines.append("")
        lines.append(f"{'model':<{w}}  {'sat':>6}  {'unsat':>6}  "
                     f"{'unknown':>7}  {'decided':>7}")
        for name, c in sorted(agg["models"].items()):
            lines.append(f"{name:<{w}}  {c['sat']:>6}  {c['unsat']:>6}  "
                         f"{c['unknown']:>7}  {c['sat'] + c['unsat']:>7}")
        v = agg["verdicts"]
        lines.append(f"{'TOTAL':<{w}}  {v['sat']:>6}  {v['unsat']:>6}  "
                     f"{v['unknown']:>7}  {agg['decided']:>7}")
    if agg.get("segments"):
        w = max(max(len(k) for k in agg["segments"]), len("mega segments"))
        lines.append("")
        lines.append(f"{'mega segments':<{w}}  {'done':>5}  {'total':>5}  "
                     f"{'partitions':>10}")
        for phase, row in agg["segments"].items():
            lines.append(f"{phase:<{w}}  {row['done']:>5}  {row['total']:>5}  "
                         f"{row['partitions']:>10}")
    if agg.get("via"):
        lines.append("")
        lines.append("decided via: " + ", ".join(
            f"{k}={n}" for k, n in sorted(agg["via"].items())))
    if agg.get("degraded"):
        w = max(max(len(k) for k in agg["degraded"]), len("degradation reason"))
        lines.append("")
        lines.append(f"{'degradation reason':<{w}}  {'partitions':>10}")
        for reason, n in agg["degraded"].items():
            lines.append(f"{reason:<{w}}  {n:>10}")
    if agg.get("smt"):
        w = max(max(len(k) for k in agg["smt"]), len("smt outcome"))
        lines.append("")
        lines.append(f"{'smt outcome':<{w}}  {'queries':>8}")
        for reason, n in agg["smt"].items():
            lines.append(f"{reason:<{w}}  {n:>8}")
    if agg.get("integrity"):
        rows = [(counter, label, n)
                for counter, d in agg["integrity"].items()
                for label, n in d.items()]
        w = max(max(len(c) for c, _, _ in rows), len("integrity counter"))
        lw = max(max(len(lb) for _, lb, _ in rows), len("site/kind"))
        lines.append("")
        lines.append(f"{'integrity counter':<{w}}  {'site/kind':<{lw}}  "
                     f"{'count':>6}")
        for counter, label, n in rows:
            lines.append(f"{counter:<{w}}  {label:<{lw}}  {n:>6}")
        lines.append("(every count is a CAUGHT corruption — "
                     "DESIGN.md §21; a clean run has no integrity table)")
    if agg.get("shards"):
        w = max(max(len(k) for k in agg["shards"]), len("shard"))
        lines.append("")
        lines.append(f"{'shard':<{w}}  {'sat':>6}  {'unsat':>6}  "
                     f"{'unknown':>7}  {'degraded':>8}")
        for label, row in agg["shards"].items():
            lines.append(f"{label:<{w}}  {row['sat']:>6}  {row['unsat']:>6}  "
                         f"{row['unknown']:>7}  {row['degraded']:>8}")
    if agg.get("requests"):
        w = max(max(len(k) for k in agg["requests"]), len("request"))
        lines.append("")
        lines.append(f"{'request':<{w}}  {'model':>10}  {'status':>8}  "
                     f"{'wait_s':>8}  {'run_s':>8}  {'decided':>7}  {'sla':>6}")
        misses = 0
        for rid, row in agg["requests"].items():
            sla = "MISS" if row["deadline_missed"] else "ok"
            misses += int(row["deadline_missed"])
            decided = row["decided"] if row["decided"] is not None else "-"
            lines.append(f"{rid:<{w}}  {row['model']:>10}  "
                         f"{row['status']:>8}  {row['queue_wait_s']:>8.3f}  "
                         f"{row['run_s']:>8.3f}  {decided:>7}  {sla:>6}")
        lines.append(f"requests: {len(agg['requests'])}   "
                     f"deadline misses: {misses}")
    if agg.get("replicas"):
        lines.append("")
        lines.append(f"{'replica':<8}  {'pid':>8}  {'restarts':>8}  "
                     f"{'deaths':>20}  {'re-homed':>8}  {'lease_age':>9}  "
                     f"{'cache_hit':>9}  {'launch/m':>8}")
        for idx, row in agg["replicas"].items():
            deaths = ",".join(f"{k}={n}" for k, n in
                              sorted(row["deaths"].items())) or "-"
            lease = f"{row['last_lease_age_s']:.2f}s" \
                if row.get("last_lease_age_s") is not None else "-"
            hit = f"{row['exec_cache_hit_rate']:.0%}" \
                if row.get("exec_cache_hit_rate") is not None else "-"
            lpm = f"{row['launches_per_model']:.1f}" \
                if row.get("launches_per_model") is not None else "-"
            label = f"{idx}*" if row.get("abandoned") else str(idx)
            lines.append(f"{label:<8}  {row['pid'] or '-':>8}  "
                         f"{row['restarts']:>8}  {deaths:>20}  "
                         f"{row['rehomed']:>8}  {lease:>9}  "
                         f"{hit:>9}  {lpm:>8}")
        if any(r.get("abandoned") for r in agg["replicas"].values()):
            lines.append("(* = slot abandoned after its restart budget)")
    if agg.get("lock_edges"):
        rows = agg["lock_edges"]
        w = max(max(len(r["src"]) for r in rows),
                max(len(r["dst"]) for r in rows),
                len("held lock (site)"))
        lines.append("")
        lines.append(f"{'held lock (site)':<{w}}  {'then acquired':<{w}}  "
                     f"{'count':>6}")
        for r in rows:
            lines.append(f"{r['src']:<{w}}  {r['dst']:<{w}}  "
                         f"{r['count']:>6}")
        lines.append(f"observed lock-order edges: {len(rows)} "
                     f"(obs.lockprof; static graph: fairify_tpu lint)")
    if agg.get("compiles"):
        w = max(max(len(k) for k in agg["compiles"]), len("kernel"))
        lines.append("")
        lines.append(f"{'kernel':<{w}}  {'compiles':>8}  {'sigs':>4}  "
                     f"{'compile_s':>9}  {'mflops':>10}  {'temp_mb':>8}")
        for kern, row in agg["compiles"].items():
            sigs = row["signatures"] if row["signatures"] is not None else "-"
            mflops = f"{row['flops'] / 1e6:.1f}" if row["flops"] else "-"
            temp = f"{row['temp_bytes'] / 1e6:.2f}" \
                if row["temp_bytes"] is not None else "-"
            lines.append(f"{kern:<{w}}  {row['count']:>8}  {sigs:>4}  "
                         f"{row['total_s']:>9.3f}  {mflops:>10}  {temp:>8}")
    return "\n".join(lines)


def _bucket_labels(edges: List[float]) -> List[str]:
    """Human-readable bucket ranges for the fixed-edge funnel histograms
    (bucket rule ``idx = Σ (v >= edge)`` — see obs.funnel.EDGES)."""
    labels = [f"< {edges[0]:g}"]
    for i in range(1, len(edges)):
        labels.append(f"[{edges[i - 1]:g}, {edges[i]:g})")
    labels.append(f">= {edges[-1]:g}")
    return labels


def render_funnel(agg: dict) -> str:
    """``--funnel`` tables: where do boxes die? (DESIGN.md §20)

    Terminal-state counts with shares, the stage-0 certified-margin /
    attack-gap histograms, and per-layer bound-looseness attribution per
    model (which layer's interval widths the certificates are losing to).
    """
    from fairify_tpu.obs import funnel as funnel_mod

    fun = agg.get("funnel") or {}
    states = fun.get("states") or {}
    if not states and not fun.get("margin_hist") and not fun.get("looseness"):
        return "no funnel telemetry in these logs"
    lines: List[str] = []
    if states:
        order = {s: i for i, s in enumerate(funnel_mod.STATES)}
        total = sum(states.values())
        w = max(max(len(s) for s in states), len("funnel state"))
        lines.append(f"{'funnel state':<{w}}  {'partitions':>10}  {'share':>7}")
        for s in sorted(states, key=lambda s: (order.get(s, len(order)), s)):
            lines.append(f"{s:<{w}}  {states[s]:>10}  "
                         f"{100.0 * states[s] / total:>6.1f}%")
        lines.append(f"decided fraction: "
                     f"{fun.get('decided_fraction', 0.0):.4f}  "
                     f"(of {total} classified partitions)")
    mh = fun.get("margin_hist")
    if mh:
        labels = _bucket_labels(mh["edges"])
        w = max(max(len(lb) for lb in labels), len("stage-0 bucket"))
        lines.append("")
        lines.append(f"{'stage-0 bucket':<{w}}  {'cert margin':>11}  "
                     f"{'attack gap':>10}")
        for lbl, m, g in zip(labels, mh["margin"], mh["gap"]):
            if m or g:  # all-empty rows add noise, not information
                lines.append(f"{lbl:<{w}}  {m:>11}  {g:>10}")
    for model, per in (fun.get("looseness") or {}).items():
        tot = sum(per) or 1.0
        lines.append("")
        lines.append(f"bound looseness {model} "
                     f"(Σ pre-activation ub−lb per layer)")
        for i, v in enumerate(per):
            lines.append(f"  layer {i}: {v:>14.3f}  ({100.0 * v / tot:.1f}%)")
    return "\n".join(lines)


def main(paths: List[str], json_out: str = None, as_json: bool = False,
         trace_dir: str = None, funnel: bool = False) -> int:
    """CLI body for ``fairify_tpu report`` (returns an exit code)."""
    import os
    import sys

    missing = [p for p in paths if not os.path.isfile(p)]
    if missing:
        print(f"no such event log: {missing}", file=sys.stderr)
        return 2
    agg = aggregate(paths)
    if trace_dir:
        shards = trace_mod.shard_paths(trace_dir)
        merged = os.path.join(trace_dir, "merged.chrome.json")
        n_events = trace_mod.write_chrome_trace_merged(shards, merged)
        agg["critical_paths"] = critical_paths(shards)
        agg["merged_chrome"] = {"path": merged, "shards": len(shards),
                                "events": n_events}
        print(f"report: merged {len(shards)} shard(s), {n_events} events "
              f"-> {merged} (load in Perfetto / chrome://tracing)",
              file=sys.stderr)
    if agg.get("skipped_lines"):
        print(f"report: skipped {agg['skipped_lines']} torn/truncated "
              f"line(s) across {agg['files']} log(s)", file=sys.stderr)
    if as_json:
        print(json.dumps(agg))
    else:
        print(render(agg))
        if funnel:
            print()
            print(render_funnel(agg))
        if agg.get("critical_paths"):
            print()
            print(render_critical_paths(agg["critical_paths"]))
    if json_out:
        with open(json_out, "w") as fp:
            json.dump(agg, fp, indent=2)
    return 0

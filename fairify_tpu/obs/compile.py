"""Compile & device-memory observability: ``obs_jit``, the jit-kernel registry.

Host wall time and device-launch economy are first-class (PRs 1-2), but the
other half of TPU cost was invisible: a cold XLA compile of the fused
stage-0 kernel costs tens of seconds over the tunnelled link, a shape- or
static-arg change silently recompiles mid-sweep, and nothing reported the
executables' FLOPs or HBM footprint — the quantities that bound batch and
partition sizing.  :func:`obs_jit` is a drop-in for ``jax.jit`` that makes
all of it observable:

* every kernel is **registered under a stable name** (module-qualified,
  e.g. ``engine.certify_attack``) in a process-wide registry
  (:func:`kernels`);
* each distinct (abstract-shape signature, static-arg key) triggers one
  explicit trace/lower/compile, recorded as a ``compile.<kernel>`` span
  carrying the signature and static key, and counted in per-kernel
  instruments: ``xla_compiles`` counter, ``xla_compile_seconds`` histogram,
  ``xla_kernel_signatures`` gauge — so recompiles from shape churn (ragged
  last chunks, per-architecture family stacks) are detected *and
  attributed* to the kernel and signature that caused them;
* on a kernel's **first** compile the executable's ``cost_analysis()``
  (FLOPs, bytes accessed) and ``memory_analysis()`` (argument / output /
  temp bytes) land in gauges and on the compile span — graceful no-op when
  a backend doesn't implement them;
* long compiles are flagged live through the active heartbeat
  (``compiling <kernel>…``), so a silent multi-second pause is attributed
  instead of looking like a hang.

Mechanics: ``obs_jit`` keeps its own executable cache keyed by the dynamic
arguments' abstract avals (+ shardings) and the static-arg values.  A miss
runs the explicit AOT path (``jitted.lower(...).compile()``) under the
compile span; a hit calls the cached executable directly.  Calls made while
tracing (a kernel composed inside another jit) and any AOT failure fall
back to the plain ``jax.jit`` path, counted in ``xla_compile_fallbacks`` —
observability must never change results or availability.

Per-kernel totals (:func:`snapshot_totals` / :func:`totals_delta`) feed the
sweep's throughput JSON (``compile_s`` / ``n_compiles`` /
``peak_temp_bytes``) and bench's warm-vs-timed compile split; the
``compile.<kernel>`` spans and the metrics snapshot feed ``fairify_tpu
report``'s per-kernel compile table.

**Persistent executable cache** (:func:`enable_exec_cache`, DESIGN.md §15):
because every miss already runs the explicit ``lower()``+``compile()`` AOT
path under a stable :meth:`ObsJit.signature_key`, compiled executables can
be serialized to disk (``jax.experimental.serialize_executable``) and a
fresh process — a restarted server, a new fleet replica — warms from the
cache instead of paying the 61–81 %-of-cold-wall compile tax (PERF.md)
again.  The contract is *never trust the disk*:

* entries are keyed by a SHA-256 of (kernel name, jax+jaxlib versions,
  backend platform, device kind, ``repr(signature_key)``) — any drift in
  any component is a different key, so stale executables are unreachable,
  not mis-loaded;
* each entry carries a magic header + checksum over the payload and embeds
  the full identity string; truncation, corruption, or an identity
  mismatch quarantines the entry to ``<entry>.corrupt`` (counted in
  ``exec_cache_errors``) and the kernel recompiles — a bad cache can cost
  time, never correctness;
* writes are write-tmp → fsync → atomic ``os.replace`` (the
  ``resilience.journal`` pattern), so replicas racing the same key never
  tear an entry — last writer wins a byte-identical executable;
* a disk hit counts in ``exec_cache_hits`` (+ ``exec_cache_load_seconds``)
  and does NOT bump ``xla_compiles`` — the warm-restart health gate stays
  ``xla_compiles == 0``.

The cache is opt-in (``fairify_tpu serve --exec-cache`` /
``FAIRIFY_TPU_EXEC_CACHE_DIR``): batch runs keep their per-process compile
accounting untouched.
"""
from __future__ import annotations

import hashlib
import inspect
import os
import pickle
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Set, Tuple

import jax

from fairify_tpu.obs import heartbeat as heartbeat_mod
from fairify_tpu.obs import metrics as metrics_mod
from fairify_tpu.obs import trace as trace_mod

try:  # public since jax 0.4.x; guarded so a rename degrades to fallback keys
    from jax.api_util import shaped_abstractify as _abstractify
except (ImportError, AttributeError):  # pragma: no cover - version drift
    _abstractify = None

# Sentinel: this signature's AOT path failed — serve it via plain jax.jit.
_FALLBACK = object()

# --- persistent executable cache (module-global, opt-in) -------------------
_EXEC_MAGIC = b"FAIRIFY-EXEC-V1\n"
_exec_cache_lock = threading.Lock()
_exec_cache_dir: Optional[str] = None


def enable_exec_cache(path: Optional[str] = None) -> str:
    """Turn on the on-disk executable cache (idempotent; returns the dir).

    ``path`` defaults to ``$FAIRIFY_TPU_EXEC_CACHE_DIR`` or
    ``~/.cache/fairify_tpu/exec``; entries are additionally keyed by
    backend + device kind, so one directory is safe to share across
    platform selections (unlike raw XLA dumps, a mismatched entry is
    unreachable rather than loadable).
    """
    global _exec_cache_dir
    path = path or os.environ.get(
        "FAIRIFY_TPU_EXEC_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "fairify_tpu",
                     "exec"))
    os.makedirs(path, exist_ok=True)
    with _exec_cache_lock:
        _exec_cache_dir = path
    return path


def disable_exec_cache() -> None:
    global _exec_cache_dir
    with _exec_cache_lock:
        _exec_cache_dir = None


def exec_cache_dir() -> Optional[str]:
    with _exec_cache_lock:
        return _exec_cache_dir

# Re-entrancy flag: >0 while an ObsJit is being traced FOR ANALYSIS
# (lowered_for_analysis).  Nested obs_jit kernels called during that trace
# hit __call__'s tracer branch exactly like production composition, but an
# analysis trace must not bump trace-inline accounting — the IR sweep
# promises zero effect on the metrics real runs are gated on.
_analysis_trace = threading.local()


def _in_analysis_trace() -> bool:
    return getattr(_analysis_trace, "depth", 0) > 0


@dataclass
class KernelStats:
    """Process-cumulative per-kernel compile accounting (never reset — the
    metrics registry holds the per-run-resettable view of the same events)."""

    name: str
    n_compiles: int = 0
    compile_s: float = 0.0  # total trace+lower+compile seconds
    fallbacks: int = 0  # calls served by plain jax.jit (AOT path unusable)
    trace_inlines: int = 0  # calls seen while tracing (outer jit owns them)
    # Persistent-cache accounting: executables served from / written to the
    # on-disk cache (enable_exec_cache).  A disk hit is NOT a compile — the
    # warm-restart health gate is n_compiles == 0 with cache_hits > 0.
    cache_hits: int = 0
    cache_stores: int = 0
    cache_load_s: float = 0.0
    signatures: Set[Any] = field(default_factory=set)
    # Signatures whose compiles were served ONLY by the plain-jit fallback:
    # they never reach `signatures`, so without this set a kernel that only
    # ever fell back would look like it never compiled at all (the
    # ir-recompile pass warns on exactly that shape).
    fallback_signatures: Set[Any] = field(default_factory=set)
    # First-compile executable analyses (None until known / unavailable).
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    arg_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None

    def as_dict(self) -> dict:
        return {
            "n_compiles": self.n_compiles,
            "compile_s": self.compile_s,
            "fallbacks": self.fallbacks,
            "trace_inlines": self.trace_inlines,
            "cache_hits": self.cache_hits,
            "cache_stores": self.cache_stores,
            "cache_load_s": self.cache_load_s,
            "n_signatures": len(self.signatures),
            "n_fallback_signatures": len(self.fallback_signatures),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "arg_bytes": self.arg_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "generated_code_bytes": self.generated_code_bytes,
        }


def _default_name(fun) -> str:
    mod = getattr(fun, "__module__", "") or ""
    return f"{mod.rsplit('.', 1)[-1]}.{fun.__name__.lstrip('_')}"


def _leaf_key(leaf):
    """Hashable abstract signature of one dynamic leaf: aval + sharding.

    The aval (shape/dtype/weak-type) is what decides a retrace; the sharding
    is part of the compiled executable's contract on mesh runs, so two
    identically-shaped but differently-sharded calls must not share an
    executable.
    """
    sharding = getattr(leaf, "sharding", None) if isinstance(leaf, jax.Array) \
        else None
    if _abstractify is not None:
        return (_abstractify(leaf), sharding)
    return (type(leaf).__name__, getattr(leaf, "shape", None),
            str(getattr(leaf, "dtype", type(leaf).__name__)), sharding)


def _sig_str(avals) -> str:
    """Compact human signature for span attrs: ``f32[2048,13] x2, ...``."""
    parts = []
    for aval, _sharding in avals:
        try:
            s = aval.str_short()
        except AttributeError:
            s = str(aval)
        if parts and parts[-1][0] == s:
            parts[-1][1] += 1
        else:
            parts.append([s, 1])
    return ", ".join(s if n == 1 else f"{s} x{n}" for s, n in parts)


class ObsJit:
    """``jax.jit`` wrapper with per-kernel compile registry + accounting.

    Call-compatible with the jitted function (including positional or
    keyword static args); additionally exposes ``__wrapped__`` (the raw
    function, for vmap composition) and ``lower`` (the AOT entry the
    profiling scripts use).
    """

    def __init__(self, fun, name: Optional[str] = None,
                 static_argnames: Tuple[str, ...] = (), register: bool = True,
                 **jit_kwargs):
        if isinstance(static_argnames, str):
            static_argnames = (static_argnames,)
        self._fun = fun
        self.__wrapped__ = fun
        self.__name__ = getattr(fun, "__name__", "jit_fn")
        self.__doc__ = getattr(fun, "__doc__", None)
        self.name = name or _default_name(fun)
        self._static = tuple(static_argnames)
        self._jit_kwargs = dict(jit_kwargs)
        self._jitted = jax.jit(fun, static_argnames=static_argnames or None,
                               **jit_kwargs)
        try:
            self._pos_names = tuple(
                p.name for p in inspect.signature(fun).parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD))
        except (TypeError, ValueError):  # pragma: no cover - builtins etc.
            self._pos_names = ()
        self._lock = threading.Lock()
        self._execs: Dict[Any, Any] = {}
        self.stats = KernelStats(self.name)
        if register:
            _KERNELS[self.name] = self

    # -- plumbing ----------------------------------------------------------
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def lowered_for_analysis(self, *args, **kwargs):
        """Traced (jaxpr-bearing) view for the IR analysis suite.

        The same explicit AOT entry `_compile` drives, minus every side
        effect: no executable cache write, no compile span, no metrics —
        analysis lowering under representative avals must never pollute
        the compile accounting real sweeps are gated on.  That includes
        NESTED kernels: tracing an outer kernel re-enters every composed
        obs_jit through ``__call__``'s tracer branch, so trace-inline
        counting is suspended for the duration.  The returned ``Traced``
        exposes ``.jaxpr`` (closed) and ``.lower()``.
        """
        _analysis_trace.depth = getattr(_analysis_trace, "depth", 0) + 1
        try:
            return self._jitted.trace(*args, **kwargs)
        finally:
            _analysis_trace.depth -= 1

    def signature_key(self, *args, **kwargs):
        """The executable-cache key this call WOULD dispatch on.

        Ground truth for the ``ir-recompile`` pass: two call shapes share
        a compiled executable iff their keys are equal.  Raises on an
        unhashable key — exactly the calls `__call__` serves via the
        plain-jit fallback.
        """
        dyn_args, dyn_kwargs, statics = self._split(args, kwargs)
        leaves, treedef = jax.tree_util.tree_flatten((dyn_args, dyn_kwargs))
        avals = tuple(_leaf_key(l) for l in leaves)
        key = (avals, treedef, statics)
        hash(key)
        return key

    def _split(self, args, kwargs):
        """(dyn_args, dyn_kwargs, static_items) preserving call structure."""
        if not self._static:
            return args, kwargs, ()
        statics = []
        dyn_args = []
        for i, a in enumerate(args):
            pname = self._pos_names[i] if i < len(self._pos_names) else None
            if pname in self._static:
                statics.append((pname, a))
            else:
                dyn_args.append(a)
        dyn_kwargs = {}
        for k, v in kwargs.items():
            if k in self._static:
                statics.append((k, v))
            else:
                dyn_kwargs[k] = v
        return tuple(dyn_args), dyn_kwargs, tuple(sorted(statics,
                                                         key=lambda kv: kv[0]))

    def _note_fallback(self, key=None) -> None:
        """Count one plain-jit-served call; register its signature when the
        key is derivable, so a kernel that ONLY ever falls back is still
        attributable (satellite of the ir-recompile pass: such a kernel
        never reaches `stats.signatures` and is invisible to IR analysis).
        """
        self.stats.fallbacks += 1
        if key is not None:
            self.stats.fallback_signatures.add(key)
        metrics_mod.registry().counter("xla_compile_fallbacks").inc(
            kernel=self.name)

    # -- call path ---------------------------------------------------------
    def __call__(self, *args, **kwargs):
        dyn_args, dyn_kwargs, statics = self._split(args, kwargs)
        leaves, treedef = jax.tree_util.tree_flatten((dyn_args, dyn_kwargs))
        if any(isinstance(l, jax.core.Tracer) for l in leaves):
            # Composed inside an outer trace: the outer kernel owns the
            # compile; inline through the plain jit path.  Counted under a
            # distinct series of the fallback metric (kind="trace") — a
            # kernel served ONLY this way registers no signatures and the
            # ir-recompile pass must be able to see that.  Analysis traces
            # are exempt (lowered_for_analysis must leave accounting
            # untouched).
            if not _in_analysis_trace():
                self.stats.trace_inlines += 1
                metrics_mod.registry().counter("xla_compile_fallbacks").inc(
                    kernel=self.name, kind="trace")
            return self._jitted(*args, **kwargs)
        try:
            avals = tuple(_leaf_key(l) for l in leaves)
            key = (avals, treedef, statics)
            hash(key)
        except Exception:
            self._note_fallback()
            return self._jitted(*args, **kwargs)
        entry = self._execs.get(key)
        if entry is None:
            entry = self._compile(key, args, kwargs, statics, avals)
        if entry is _FALLBACK:
            return self._jitted(*args, **kwargs)
        try:
            return entry(*dyn_args, **dyn_kwargs)
        except Exception:
            # Executable/argument mismatch (e.g. layout or sharding drift
            # invisible to the key): never fail the kernel over accounting.
            self._note_fallback(key)
            self._execs[key] = _FALLBACK
            return self._jitted(*args, **kwargs)

    # -- persistent executable cache (DESIGN.md §15) -----------------------

    def _exec_identity(self, key) -> str:
        """Full identity of one executable: anything that could make a
        stored executable wrong for this call must be in here."""
        backend = jax.default_backend()
        try:
            dev_kind = jax.devices()[0].device_kind
        except (RuntimeError, IndexError):  # pragma: no cover - init edge
            dev_kind = "?"
        import jaxlib

        return "|".join((self.name, jax.__version__, jaxlib.__version__,
                         backend, dev_kind, repr(key)))

    def _exec_path(self, cache_dir: str, ident: str) -> str:
        h = hashlib.sha256(ident.encode()).hexdigest()[:32]
        safe = self.name.replace("/", "_")
        return os.path.join(cache_dir, f"{safe}.{h}.exec")

    def _load_cached_exec(self, cache_dir: str, key):
        """Compiled executable from disk, or None (miss / rejected entry).

        Never trusts the file: magic, checksum, and the embedded identity
        string must all verify, and deserialization itself may fail (e.g.
        an XLA drift the version fields didn't capture) — any failure
        quarantines the entry to ``.corrupt`` and the caller recompiles.
        """
        ident = self._exec_identity(key)
        path = self._exec_path(cache_dir, ident)
        try:
            with open(path, "rb") as fp:
                raw = fp.read()
        except OSError:
            return None
        reg = metrics_mod.registry()
        t0 = time.perf_counter()
        try:
            if not raw.startswith(_EXEC_MAGIC):
                raise ValueError("bad magic")
            body = raw[len(_EXEC_MAGIC):]
            digest, _, payload = body.partition(b"\n")
            if hashlib.sha256(payload).hexdigest().encode() != digest:
                raise ValueError("checksum mismatch (truncated or corrupt)")
            meta = pickle.loads(payload)
            if meta.get("ident") != ident:
                raise ValueError(f"identity mismatch: "
                                 f"{meta.get('ident', '?')[:120]!r}")
            from jax.experimental import serialize_executable as se

            compiled = se.deserialize_and_load(
                meta["blob"], meta["in_tree"], meta["out_tree"])
        except BaseException as exc:
            from fairify_tpu.resilience.supervisor import classify

            if classify(exc) == "propagate":
                raise
            # Quarantine, count, recompile — a bad entry must never be
            # re-parsed on the next miss, and never trusted.
            try:
                os.replace(path, f"{path}.corrupt")
            except OSError:
                pass
            reg.counter("exec_cache_errors").inc(kernel=self.name)
            trace_mod.event("degraded", site="exec_cache", kernel=self.name,
                            error=type(exc).__name__, detail=str(exc)[:200])
            return None
        dur = time.perf_counter() - t0
        self.stats.cache_hits += 1
        self.stats.cache_load_s += dur
        reg.counter("exec_cache_hits").inc(kernel=self.name)
        reg.histogram("exec_cache_load_seconds").observe(dur,
                                                         kernel=self.name)
        return compiled

    def _store_cached_exec(self, cache_dir: str, key, compiled) -> None:
        """Serialize + atomically publish one executable (best effort).

        Write-tmp → fsync → ``os.replace``: concurrent replicas racing the
        same key each publish a complete entry and the last rename wins —
        readers can never observe a torn file.  Serialization failures
        (e.g. a sharded executable the backend won't export) are counted
        and skipped; the cache degrades to a smaller cache, never an error.
        """
        ident = self._exec_identity(key)
        path = self._exec_path(cache_dir, ident)
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            from jax.experimental import serialize_executable as se

            blob, in_tree, out_tree = se.serialize(compiled)
            payload = pickle.dumps({"ident": ident, "blob": blob,
                                    "in_tree": in_tree,
                                    "out_tree": out_tree})
            digest = hashlib.sha256(payload).hexdigest().encode()
            with open(tmp, "wb") as fp:
                fp.write(_EXEC_MAGIC + digest + b"\n" + payload)
                fp.flush()
                os.fsync(fp.fileno())
            os.replace(tmp, path)
        except BaseException as exc:
            from fairify_tpu.resilience.supervisor import classify

            if classify(exc) == "propagate":
                raise
            try:
                os.remove(tmp)
            except OSError:
                pass
            metrics_mod.registry().counter("exec_cache_store_failures").inc(
                kernel=self.name)
            trace_mod.event("degraded", site="exec_cache", kernel=self.name,
                            error=type(exc).__name__, detail=str(exc)[:200])
            return
        self.stats.cache_stores += 1
        metrics_mod.registry().counter("exec_cache_stores").inc(
            kernel=self.name)

    def _compile(self, key, args, kwargs, statics, avals):
        cache_dir = exec_cache_dir()
        if cache_dir is not None:
            cached = self._load_cached_exec(cache_dir, key)
            if cached is not None:
                with trace_mod.span(f"execload.{self.name}",
                                    kernel=self.name,
                                    signature=_sig_str(avals), cache="hit"):
                    pass
                with self._lock:
                    self.stats.signatures.add(key)
                    n_sigs = len(self.stats.signatures)
                    self._execs[key] = cached
                reg = metrics_mod.registry()
                reg.gauge("xla_kernel_signatures").set(n_sigs,
                                                       kernel=self.name)
                if self.stats.temp_bytes is None:
                    # First executable this process has seen for the
                    # kernel: record the analyses the fresh-compile path
                    # would have (backend-optional, guarded inside).
                    with trace_mod.span(f"compileinfo.{self.name}",
                                        kernel=self.name) as sp:
                        self._record_analysis(cached, sp)
                return cached
        heartbeat_mod.notify_compile(self.name)
        static_str = ", ".join(f"{k}={v!r}" for k, v in statics)
        with trace_mod.span(f"compile.{self.name}", kernel=self.name,
                            signature=_sig_str(avals),
                            static=static_str) as sp:
            t0 = time.perf_counter()
            try:
                # Named fault site for the chaos suite: an injected compile
                # fault exercises exactly the fallback below (the kernel is
                # served by plain jax.jit — results unchanged, the miss
                # counted), so a flaky AOT path degrades observability only.
                from fairify_tpu.resilience import faults as faults_mod

                faults_mod.check("compile")
                lowered = self._jitted.lower(*args, **kwargs)
                t1 = time.perf_counter()
                compiled = lowered.compile()
                t2 = time.perf_counter()
            except Exception as exc:
                from fairify_tpu.resilience.supervisor import classify

                if classify(exc) == "propagate":  # injected crash-kind etc.
                    raise
                self._note_fallback(key)
                with self._lock:
                    self._execs[key] = _FALLBACK
                return _FALLBACK
            sp.set(lower_s=round(t1 - t0, 6), compile_s=round(t2 - t1, 6))
            dur = t2 - t0
            reg = metrics_mod.registry()
            with self._lock:
                first = self.stats.n_compiles == 0
                self.stats.n_compiles += 1
                self.stats.compile_s += dur
                self.stats.signatures.add(key)
                n_sigs = len(self.stats.signatures)
                self._execs[key] = compiled
            reg.counter("xla_compiles").inc(kernel=self.name)
            reg.histogram("xla_compile_seconds").observe(dur, kernel=self.name)
            reg.gauge("xla_kernel_signatures").set(n_sigs, kernel=self.name)
            if first:
                self._record_analysis(compiled, sp)
        if cache_dir is not None:
            self._store_cached_exec(cache_dir, key, compiled)
        return compiled

    def _record_analysis(self, compiled, sp) -> None:
        """First-compile FLOPs / memory footprint → gauges + the compile span.

        Both analyses are backend-optional (the CPU backend grew them late;
        some platforms return None/raise) — absence degrades to missing
        attrs, never an error.
        """
        st = self.stats
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if isinstance(ca, dict):
                flops = ca.get("flops")
                st.flops = float(flops) if flops is not None else None
                acc = ca.get("bytes accessed")
                st.bytes_accessed = float(acc) if acc is not None else None
        except Exception:
            pass
        try:
            ma = compiled.memory_analysis()
            st.arg_bytes = int(ma.argument_size_in_bytes)
            st.output_bytes = int(ma.output_size_in_bytes)
            st.temp_bytes = int(ma.temp_size_in_bytes)
            st.generated_code_bytes = int(ma.generated_code_size_in_bytes)
        except Exception:
            pass
        reg = metrics_mod.registry()
        gauges = (("xla_kernel_flops", st.flops),
                  ("xla_kernel_bytes_accessed", st.bytes_accessed),
                  ("xla_kernel_arg_bytes", st.arg_bytes),
                  ("xla_kernel_output_bytes", st.output_bytes),
                  ("xla_kernel_temp_bytes", st.temp_bytes))
        for gname, v in gauges:
            if v is not None:
                reg.gauge(gname).set(v, kernel=self.name)
        attrs = {"flops": st.flops, "bytes_accessed": st.bytes_accessed,
                 "arg_bytes": st.arg_bytes, "output_bytes": st.output_bytes,
                 "temp_bytes": st.temp_bytes}
        sp.set(**{k: v for k, v in attrs.items() if v is not None})


# ---------------------------------------------------------------------------
# Registry + totals (throughput JSON / bench warm-split consumers)
# ---------------------------------------------------------------------------

_KERNELS: Dict[str, ObsJit] = {}


def obs_jit(fun=None, *, name: Optional[str] = None,
            static_argnames: Tuple[str, ...] = (), register: bool = True,
            **jit_kwargs):
    """Drop-in for ``jax.jit`` / ``partial(jax.jit, static_argnames=...)``.

    Usable bare (``@obs_jit``), with options
    (``@obs_jit(static_argnames=("k",))``), or call-style
    (``obs_jit(fn, name="engine.certify", static_argnames=("k",))``).
    ``register=False`` keeps the kernel out of the process registry —
    for fixture/scratch kernels that want the accounting machinery
    without appearing in :func:`kernels` (the IR analysis sweep iterates
    that registry).
    """
    if fun is None:
        return lambda f: obs_jit(f, name=name, static_argnames=static_argnames,
                                 register=register, **jit_kwargs)
    return ObsJit(fun, name=name, static_argnames=static_argnames,
                  register=register, **jit_kwargs)


def kernels() -> Dict[str, ObsJit]:
    """Name → registered kernel (import order; stable within a process)."""
    return dict(_KERNELS)


def kernel_stats() -> Dict[str, dict]:
    """Name → cumulative stats dict (JSON-ready) for every registered kernel."""
    return {name: k.stats.as_dict() for name, k in sorted(_KERNELS.items())}


def snapshot_totals() -> Dict[str, object]:
    """Process-cumulative compile totals (pair with :func:`totals_delta`)."""
    n = c = f = 0.0
    per_kernel: Dict[str, int] = {}
    for k in _KERNELS.values():
        st = k.stats
        n += st.n_compiles
        c += st.compile_s
        f += st.fallbacks
        per_kernel[k.name] = st.n_compiles
    return {"n_compiles": int(n), "compile_s": c, "fallbacks": int(f),
            "per_kernel": per_kernel}


def totals_delta(before: Dict[str, object],
                 after: Optional[Dict[str, object]] = None) -> Dict[str, float]:
    """Per-run compile record: ``after - before`` for the cumulative counts.

    ``peak_temp_bytes`` is the largest per-executable temp footprint among
    the kernels that actually compiled WITHIN the window — a run that
    compiles nothing (warm) reports 0, and an earlier run's big family
    kernels are never attributed to a later model's record.
    """
    if after is None:
        after = snapshot_totals()
    before_pk = before.get("per_kernel", {})
    peak = 0
    for name, n_after in after.get("per_kernel", {}).items():
        if n_after > before_pk.get(name, 0):
            temp = _KERNELS[name].stats.temp_bytes if name in _KERNELS else None
            if temp:
                peak = max(peak, temp)
    return {
        "n_compiles": int(after["n_compiles"] - before.get("n_compiles", 0)),
        "compile_s": after["compile_s"] - before.get("compile_s", 0.0),
        "fallbacks": int(after["fallbacks"] - before.get("fallbacks", 0)),
        "peak_temp_bytes": int(peak),
    }

"""Named, labelled, resettable metrics: counters, gauges, histograms.

The seed's only instruments were a module-global ``_LAUNCHES`` int (never
reset, so absolute reads across sweeps in one process were stale) and the
ad-hoc fields of :class:`fairify_tpu.utils.profiling.ThroughputCounter`.
This registry replaces both with named instruments that

* carry **labels** (``counter.inc(verdict="sat", via="stage0")``), so one
  instrument covers a verdict × phase matrix instead of five attributes;
* are **resettable** (:meth:`MetricsRegistry.reset`) between runs, so
  per-run deltas need no caller-side subtraction;
* **snapshot** to plain JSON (:meth:`MetricsRegistry.snapshot`) — the
  record the tracer appends to the event log on close and ``fairify_tpu
  report`` aggregates.

Everything is host-side Python on the sweep's bookkeeping path (never
inside a jit), and every access — reads included — takes one small lock
(the ``lock-discipline`` lint enforces this): thread-safe for the
multi-threaded span/heartbeat consumers, negligible against the ~110 ms
device-launch floor the counters exist to account for.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

# Default latency buckets (seconds): spans partition decisions from
# sub-millisecond ledger replays to the 100 s soft-timeout tail.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0, 500.0)


def _key(labels: Dict[str, object]) -> tuple:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic (between resets) named counter with optional labels."""

    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._series: Dict[tuple, float] = {}

    def inc(self, n: float = 1, **labels) -> None:
        k = _key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_key(labels), 0)

    def total(self) -> float:
        with self._lock:
            return sum(self._series.values())

    def reset(self) -> None:
        with self._lock:
            self._series.clear()

    def snapshot(self) -> list:
        with self._lock:
            return [{"labels": dict(k), "value": v}
                    for k, v in sorted(self._series.items())]


class Gauge:
    """Last-write-wins named value with optional labels."""

    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._series: Dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_key(labels)] = value

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._series.get(_key(labels))

    def reset(self) -> None:
        with self._lock:
            self._series.clear()

    def snapshot(self) -> list:
        with self._lock:
            return [{"labels": dict(k), "value": v}
                    for k, v in sorted(self._series.items())]


class Histogram:
    """Cumulative-bucket histogram (Prometheus-style ``le`` bounds).

    ``counts()[i]`` is the number of observations ≤ ``buckets[i]``; the
    final slot counts the overflow (> last bound).
    """

    kind = "histogram"

    def __init__(self, name: str, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        # label key -> [per-bucket counts..., overflow], running sum, count
        self._series: Dict[tuple, list] = {}

    def observe(self, value: float, **labels) -> None:
        k = _key(labels)
        with self._lock:
            s = self._series.get(k)
            if s is None:
                s = self._series[k] = [[0] * (len(self.buckets) + 1), 0.0, 0]
            for i, b in enumerate(self.buckets):
                if value <= b:
                    s[0][i] += 1
                    break
            else:
                s[0][-1] += 1
            s[1] += value
            s[2] += 1

    def counts(self, **labels) -> list:
        with self._lock:
            s = self._series.get(_key(labels))
            return list(s[0]) if s else [0] * (len(self.buckets) + 1)

    def sum(self, **labels) -> float:
        with self._lock:
            s = self._series.get(_key(labels))
            return s[1] if s else 0.0

    def count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(_key(labels))
            return s[2] if s else 0

    def reset(self) -> None:
        with self._lock:
            self._series.clear()

    def snapshot(self) -> list:
        with self._lock:
            return [{"labels": dict(k), "buckets": list(self.buckets),
                     "counts": list(s[0]), "sum": s[1], "count": s[2]}
                    for k, s in sorted(self._series.items())]


class MetricsRegistry:
    """Name → instrument map; one per process by default (:func:`registry`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}

    def _get(self, name: str, factory):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = factory()
            return inst

    def counter(self, name: str) -> Counter:
        inst = self._get(name, lambda: Counter(name))
        if not isinstance(inst, Counter):
            raise TypeError(f"{name!r} is registered as a {inst.kind}")
        return inst

    def gauge(self, name: str) -> Gauge:
        inst = self._get(name, lambda: Gauge(name))
        if not isinstance(inst, Gauge):
            raise TypeError(f"{name!r} is registered as a {inst.kind}")
        return inst

    def histogram(self, name: str,
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        inst = self._get(
            name, lambda: Histogram(name, buckets or DEFAULT_BUCKETS))
        if not isinstance(inst, Histogram):
            raise TypeError(f"{name!r} is registered as a {inst.kind}")
        return inst

    def reset(self) -> None:
        """Zero every instrument (registrations survive) — per-run hygiene."""
        with self._lock:
            instruments = list(self._instruments.values())
        for inst in instruments:
            inst.reset()

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            instruments = sorted(self._instruments.items())
        return {name: {"kind": inst.kind, "series": inst.snapshot()}
                for name, inst in instruments}


def snapshot_delta(before: Dict[str, dict], after: Dict[str, dict]) -> Dict[str, dict]:
    """Per-run view of two registry snapshots: ``after`` minus ``before``.

    The process registry is cumulative (resetting it under a live consumer
    would corrupt absolute readers like the sweep's launch delta), so a
    tracer instead snapshots at activation and records the difference at
    close.  Counters and histograms subtract per labelled series (empty
    series are dropped); gauges are last-write-wins, so the ``after`` value
    is kept as-is.
    """
    out: Dict[str, dict] = {}
    for name, inst in after.items():
        base = before.get(name)
        kind = inst["kind"]
        if base is None or base["kind"] != kind or kind == "gauge":
            out[name] = inst
            continue
        base_map = {_key(s["labels"]): s for s in base["series"]}
        series = []
        for s in inst["series"]:
            b = base_map.get(_key(s["labels"]))
            if b is None:
                series.append(s)
            elif kind == "counter":
                v = s["value"] - b["value"]
                if v:
                    series.append({"labels": s["labels"], "value": v})
            else:  # histogram
                n = s["count"] - b["count"]
                if n:
                    series.append({
                        "labels": s["labels"], "buckets": s["buckets"],
                        "counts": [a - c for a, c in zip(s["counts"], b["counts"])],
                        "sum": s["sum"] - b["sum"], "count": n})
        if series:
            out[name] = {"kind": kind, "series": series}
    return out


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-default registry (the launch counter et al. live here)."""
    return _REGISTRY

"""Verification-funnel taxonomy + fixed-bucket margin statistics.

The paper's whole game is the SAT/UNSAT/UNKNOWN funnel — Fairify wins by
pruning until almost nothing reaches the solver — so every partition's
terminal state is classified into ONE of the states below and the run's
certified-margin / attack-gap distributions are kept as fixed-bucket
histograms (DESIGN.md §20).  The bucket layout is shared verbatim by the
device kernels (``verify/sweep._chunk_stats_dev`` accumulates inside the
mega-loop's ``lax.scan`` carry) and the host mirrors here, so a segment's
statistics cost one extra fetched buffer and ZERO extra launches.

Terminal states
---------------
``certified:stage0``   UNSAT by the stage-0 CROWN certificate
``attacked:stage0``    SAT by a stage-0 attack witness (exact-replayed)
``certified:bab``      UNSAT by BaB / the heuristic retry tier
``attacked:bab``       SAT by BaB / PGD / the heuristic retry tier
``smt:unsat``          UNSAT by the out-of-process SMT tier
``smt:sat``            SAT by the SMT tier
``unknown:deadline``   abandoned by the deadline (per-box or cumulative)
``unknown:budget``     abandoned by a node/attempt budget (or never
                       attempted under a budgeted ladder)
``unknown:frontier``   legacy catch-all: the host-frontier BaB (or an
                       unrecognised engine reason) could not decide it
``unknown:frontier:overflow``  the device BaB queue ran out of slots while
                       the root still had splittable boxes — a CAPACITY
                       fall, not a hardness one (raise
                       ``EngineConfig.bab_frontier_cap``)
``unknown:frontier:hard``  the device BaB ran to a bound stall / exact-leaf
                       UNKNOWN with queue room to spare: genuinely hard
``unknown:failure:<site>``  degraded by an exhausted fault site (the
                       ``<site>`` prefix of the failure record's reason,
                       e.g. ``launch.submit``)
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

#: Closed bucket taxonomy (``unknown:failure:<site>`` is open-ended).
STATES = (
    "certified:stage0",
    "attacked:stage0",
    "certified:bab",
    "attacked:bab",
    "smt:sat",
    "smt:unsat",
    "unknown:deadline",
    "unknown:budget",
    "unknown:frontier",
    "unknown:frontier:overflow",
    "unknown:frontier:hard",
)

#: Engine ``Decision.reason`` values with a dedicated funnel state.  The
#: device-BaB path (DESIGN.md §22) splits the old catch-all 'frontier' into
#: 'frontier:overflow' (queue capacity exhausted — retune, don't despair)
#: vs 'frontier:hard' (bounds stalled at full budget — genuinely hard);
#: bare 'frontier' remains the host-frontier / unrecognised-reason fallback.
_ENGINE_REASONS = ("deadline", "budget", "frontier",
                   "frontier:overflow", "frontier:hard")

# ---------------------------------------------------------------------------
# Fixed-bucket histogram layout (margins and attack gaps share it)
# ---------------------------------------------------------------------------

#: Symmetric log-ish bucket edges.  Bucket i holds values v with
#: ``EDGES[i-1] <= v < EDGES[i]`` under the rule ``idx = Σ (v >= edge)``
#: (identical on device and host: comparisons + a reduce, no searchsorted).
#: The 0.0 edge makes the certified boundary exact: margin >= 0 ⟺ certified
#: lands in buckets >= NEG_BUCKETS by construction.
EDGES = np.array([-1e4, -1e2, -10.0, -1.0, -0.1, -0.01, 0.0,
                  0.01, 0.1, 1.0, 10.0, 100.0, 1e4], dtype=np.float32)
N_BUCKETS = int(EDGES.size) + 1
#: Buckets strictly below 0 (margin < 0 / gap <= 0 side).
NEG_BUCKETS = int((EDGES <= 0.0).sum())
MARGIN_ROW, GAP_ROW = 0, 1


def bucketize(values: np.ndarray) -> np.ndarray:
    """Host mirror of the device bucket rule: ``idx = Σ (v >= edge)``."""
    v = np.asarray(values, np.float32)
    return (v[..., None] >= EDGES).sum(axis=-1).astype(np.int64)


def hist(values: np.ndarray, ok: Optional[np.ndarray] = None) -> np.ndarray:
    """(N_BUCKETS,) int64 histogram of ``values`` (rows masked by ``ok``)."""
    idx = bucketize(values).reshape(-1)
    if ok is None:
        okf = np.ones(idx.shape, dtype=bool)
    else:
        okf = np.asarray(ok, dtype=bool).reshape(-1)
    onehot = (idx[:, None] == np.arange(N_BUCKETS)[None, :]) & okf[:, None]
    return onehot.sum(axis=0).astype(np.int64)


class StageStats:
    """Host accumulator for the stage-0 margin/gap histograms.

    Fed either a packed device ``(2, N_BUCKETS)`` buffer (the mega-loop's
    scan-carry result, one per segment) or raw per-box values (the chunk
    loop's host decode) — the two paths produce bit-identical histograms
    for bit-identical margins because they share one bucket rule.
    """

    def __init__(self) -> None:
        self.hist = np.zeros((2, N_BUCKETS), dtype=np.int64)

    def add_packed(self, stats) -> None:
        self.hist += np.asarray(stats, dtype=np.int64).reshape(2, N_BUCKETS)

    def add_values(self, margin, gap, ok: Optional[np.ndarray] = None) -> None:
        self.hist[MARGIN_ROW] += hist(margin, ok)
        self.hist[GAP_ROW] += hist(gap, ok)

    def merge(self, other: "StageStats") -> None:
        self.hist += other.hist

    @property
    def margin_hist(self) -> np.ndarray:
        return self.hist[MARGIN_ROW]

    @property
    def gap_hist(self) -> np.ndarray:
        return self.hist[GAP_ROW]

    @property
    def boxes(self) -> int:
        return int(self.hist[MARGIN_ROW].sum())

    def to_payload(self) -> dict:
        """JSON-ready histogram block for throughput files / funnel events."""
        return {
            "edges": [float(e) for e in EDGES],
            "margin": [int(c) for c in self.hist[MARGIN_ROW]],
            "gap": [int(c) for c in self.hist[GAP_ROW]],
        }


# ---------------------------------------------------------------------------
# Terminal-state classification
# ---------------------------------------------------------------------------


def failure_state(failure_reason: str) -> str:
    """``unknown:failure:<site>`` from a failure record's ``site:kind`` reason."""
    site = str(failure_reason).split(":", 1)[0] or "unknown"
    return f"unknown:failure:{site}"


def classify(verdict: str, via: str, failure: Optional[str] = None,
             engine_reason: Optional[str] = None) -> str:
    """One partition's terminal funnel state.

    ``via`` is the verdict event's provenance tag (``stage0`` / ``bab`` /
    ``heuristic`` / ``smt`` / ``degraded`` / ``ledger``); ``failure`` the
    degradation reason (``site:kind``) when the partition degraded;
    ``engine_reason`` the BaB :class:`~fairify_tpu.verify.engine.Decision`
    reason for UNKNOWNs (``deadline`` | ``budget`` | ``frontier`` |
    ``frontier:overflow`` | ``frontier:hard``).
    """
    if failure is not None:
        return failure_state(failure)
    if verdict == "unsat":
        if via == "stage0":
            return "certified:stage0"
        if via == "smt":
            return "smt:unsat"
        return "certified:bab"
    if verdict == "sat":
        if via == "stage0":
            return "attacked:stage0"
        if via == "smt":
            return "smt:sat"
        return "attacked:bab"
    reason = engine_reason if engine_reason in _ENGINE_REASONS else "frontier"
    return f"unknown:{reason}"


def is_decided(state: str) -> bool:
    return not state.startswith("unknown")


class FunnelCounts:
    """Per-run terminal-state counter, mirrored into the metrics registry.

    Every ``add`` increments the labelled ``funnel_states`` counter of the
    process registry, so heartbeats and serve metrics see the LIVE funnel;
    the instance itself is the per-run tally that rides the throughput JSON
    and the per-model ``funnel`` event.
    """

    def __init__(self, mirror: bool = True) -> None:
        self.counts: Dict[str, int] = {}
        self._mirror = mirror

    def add(self, state: str, n: int = 1) -> None:
        if n <= 0:
            return
        self.counts[state] = self.counts.get(state, 0) + n
        if self._mirror:
            from fairify_tpu.obs.metrics import registry

            registry().counter("funnel_states").inc(n, state=state)

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    @property
    def decided(self) -> int:
        return sum(n for s, n in self.counts.items() if is_decided(s))

    @property
    def decided_fraction(self) -> float:
        total = self.total
        return (self.decided / total) if total else 0.0

    def to_dict(self) -> Dict[str, int]:
        return {s: self.counts[s] for s in sorted(self.counts)}


def merge_payloads(payloads) -> Optional[dict]:
    """Sum per-run funnel payloads into one (serve's span-granular sub-runs).

    Each payload is a ``ModelReport.funnel`` dict (``states`` /
    ``margin_hist`` / ``looseness``); Nones are skipped.  Returns None when
    nothing was merged, so a request with no sub-reports carries no funnel
    block instead of an all-zero one.
    """
    states: Dict[str, int] = {}
    hist = None
    loos = None
    merged = False
    for p in payloads:
        if not p:
            continue
        merged = True
        for s, n in (p.get("states") or {}).items():
            states[s] = states.get(s, 0) + int(n)
        mh = p.get("margin_hist")
        if mh:
            if hist is None:
                hist = {"edges": [float(e) for e in mh["edges"]],
                        "margin": [0] * len(mh["margin"]),
                        "gap": [0] * len(mh["gap"])}
            hist["margin"] = [a + int(b)
                              for a, b in zip(hist["margin"], mh["margin"])]
            hist["gap"] = [a + int(b) for a, b in zip(hist["gap"], mh["gap"])]
        lo = p.get("looseness")
        if lo is not None:
            if loos is None or len(loos) != len(lo):
                loos = [float(v) for v in lo]
            else:
                loos = [a + float(v) for a, v in zip(loos, lo)]
    if not merged:
        return None
    total = sum(states.values())
    decided = sum(n for s, n in states.items() if is_decided(s))
    return {"states": states, "total": total, "decided": decided,
            "decided_fraction": (decided / total) if total else 0.0,
            "margin_hist": hist, "looseness": loos}


def decided_fraction(states: Dict[str, int]) -> float:
    """Decided fraction of a funnel-state count dict (0.0 on empty)."""
    total = sum(states.values())
    if not total:
        return 0.0
    return sum(n for s, n in states.items() if is_decided(s)) / total


def live_decided() -> int:
    """Process-wide decided count from the mirrored ``funnel_states`` counter
    (heartbeat's live-funnel source; pair with a baseline captured at init)."""
    from fairify_tpu.obs.metrics import registry

    snap = registry().counter("funnel_states").snapshot()
    return int(sum(s["value"] for s in snap
                   if is_decided(s["labels"].get("state", ""))))

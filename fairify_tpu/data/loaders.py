"""Dataset loaders: CSV → encoded numpy matrices with a fixed seed-42 split.

Re-implements the reference's six loaders (``utils/verif_utils.py:46-482``)
with identical semantics — same column sets, same label/ordinal encodings,
same 85/15 split at ``random_state=42`` — so verdicts and metrics are
comparable row-for-row.  Loaders return ``LoadedDataset`` instead of bare
tuples and keep the fitted encoders for counterexample decoding
(``src/AC/Verify-AC-experiment-new2.py:344-407``).

Data files are read from a configurable root (default: the read-only
reference checkout).  ``bank-additional-full.csv`` is missing from the
reference checkout (git-LFS stub, ``.MISSING_LARGE_BLOBS``); the bank loader
falls back to the committed ``bank-additional.csv`` sample and records which
file it used.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Optional

import numpy as np
import pandas as pd
from sklearn.model_selection import train_test_split
from sklearn.preprocessing import KBinsDiscretizer, LabelEncoder, MinMaxScaler, OneHotEncoder

DEFAULT_DATA_ROOT = os.environ.get("FAIRIFY_TPU_DATA_ROOT", "/root/reference/data")
SPLIT_SEED = 42  # utils/verif_utils.py:187 — fixed across every loader
TEST_FRACTION = 0.15


@dataclass
class LoadedDataset:
    name: str
    df: pd.DataFrame
    X_train: np.ndarray
    y_train: np.ndarray
    X_test: np.ndarray
    y_test: np.ndarray
    label: str
    encoders: Dict[str, object] = field(default_factory=dict)
    notes: Dict[str, str] = field(default_factory=dict)

    @property
    def feature_columns(self):
        return [c for c in self.df.columns if c != self.label]

    @property
    def X(self) -> np.ndarray:
        return np.concatenate([self.X_train, self.X_test], axis=0)


def _split(X: pd.DataFrame, y: pd.Series):
    X_train, X_test, y_train, y_test = train_test_split(
        X, y, test_size=TEST_FRACTION, random_state=SPLIT_SEED
    )
    return (
        X_train.to_numpy().astype(np.float64),
        y_train.to_numpy().astype("int"),
        X_test.to_numpy().astype(np.float64),
        y_test.to_numpy().astype("int"),
    )


def _root(root) -> Path:
    return Path(root or DEFAULT_DATA_ROOT)


# ---------------------------------------------------------------------------
# German Credit  (utils/verif_utils.py:193-241 + utils/standard_data.py:4-65)
# ---------------------------------------------------------------------------

_GERMAN_COLUMNS = [
    "status", "month", "credit_history", "purpose", "credit_amount", "savings",
    "employment", "investment_as_income_percentage", "personal_status",
    "other_debtors", "residence_since", "property", "age", "installment_plans",
    "housing", "number_of_credits", "skill_level", "people_liable_for",
    "telephone", "foreign_worker", "credit",
]


def _german_preprocess(df: pd.DataFrame) -> pd.DataFrame:
    """Semantic grouping of German-credit codes (``utils/standard_data.py:4-65``):
    credit-history/savings/employment collapsed to coarse categories, ``sex``
    derived from ``personal_status``, label 1/2 → 1/0."""
    # 1 = male, 0 = female (utils/standard_data.py:48-51)
    status_map = {"A91": 1, "A93": 1, "A94": 1, "A92": 0, "A95": 0}
    df["sex"] = df["personal_status"].map(status_map)

    group_maps = {
        "credit_history": {"A30": "None/Paid", "A31": "None/Paid", "A32": "None/Paid",
                           "A33": "Delay", "A34": "Other"},
        "savings": {"A61": "<500", "A62": "<500", "A63": "500+", "A64": "500+", "A65": "Unknown/None"},
        "employment": {"A71": "Unemployed", "A72": "1-4 years", "A73": "1-4 years",
                       "A74": "4+ years", "A75": "4+ years"},
        "status": {"A11": "<200", "A12": "<200", "A13": "200+", "A14": "None"},
    }
    for col, mapping in group_maps.items():
        df[col] = df[col].map(mapping)
    df["credit"] = df["credit"].replace({1: 1, 2: 0})
    return df


def load_german(root=None) -> LoadedDataset:
    path = _root(root) / "german" / "german.data"
    df = pd.read_csv(path, sep=" ", header=None, names=_GERMAN_COLUMNS)
    df["age"] = (df["age"] >= 26).astype(float)  # binarized PA, verif_utils.py:204
    df = _german_preprocess(df)
    df = df.drop(columns=["personal_status"])

    encoders: Dict[str, object] = {}
    cat_feat = ["status", "credit_history", "purpose", "savings", "employment",
                "other_debtors", "property", "installment_plans", "housing",
                "skill_level", "telephone", "foreign_worker"]
    for f in cat_feat:
        le = LabelEncoder()
        df[f] = le.fit_transform(df[f])
        encoders[f] = le

    label = "credit"
    X = df.drop(columns=[label])
    y = df[label]
    X_train, y_train, X_test, y_test = _split(X, y)
    return LoadedDataset("german", df, X_train, y_train, X_test, y_test, label, encoders)


# ---------------------------------------------------------------------------
# Adult Census, label-encoded 13-feature form  (utils/verif_utils.py:119-190)
# ---------------------------------------------------------------------------

_ADULT_COLUMNS = [
    "age", "workclass", "fnlwgt", "education", "education-num", "marital-status",
    "occupation", "relationship", "race", "sex", "capital-gain", "capital-loss",
    "hours-per-week", "native-country", "income-per-year",
]


def load_adult(root=None) -> LoadedDataset:
    """The AC drivers' loader (``load_adult_ac1``): label-encode categoricals,
    20-bin-discretize capital gain/loss, binary label on >50K."""
    base = _root(root) / "adult"
    train = pd.read_csv(base / "adult.data", header=None, names=_ADULT_COLUMNS,
                        skipinitialspace=True, na_values=["?"])
    test = pd.read_csv(base / "adult.test", header=0, names=_ADULT_COLUMNS,
                       skipinitialspace=True, na_values=["?"])
    df = pd.concat([test, train], ignore_index=True)
    df = df.drop(columns=["fnlwgt"]).dropna()

    encoders: Dict[str, object] = {}
    for f in ["sex", "workclass", "education", "marital-status", "occupation",
              "relationship", "native-country", "race"]:
        le = LabelEncoder()
        df[f] = le.fit_transform(df[f])
        encoders[f] = le
    for f in ["capital-gain", "capital-loss"]:
        kb = KBinsDiscretizer(n_bins=20, encode="ordinal", strategy="uniform")
        df[f] = kb.fit_transform(df[[f]])
        encoders[f] = kb

    label = "income-per-year"
    fav = df[label].isin([">50K", ">50K."])
    df[label] = np.where(fav, 1, 0)
    X = df.drop(columns=[label])
    y = df[label]
    X_train, y_train, X_test, y_test = _split(X, y)
    return LoadedDataset("adult", df, X_train, y_train, X_test, y_test, label, encoders)


def load_adult_adf(root=None) -> LoadedDataset:
    """The ADF variant (``utils/verif_utils.py:46-116``): identical 13-feature
    encoding to :func:`load_adult`, but the label is returned one-hot
    (``pd.get_dummies(y)``, two columns) — the form the reference's ADF-style
    consumers expect."""
    base = load("adult", root)
    y_train = np.stack([1 - base.y_train, base.y_train], axis=1).astype("int")
    y_test = np.stack([1 - base.y_test, base.y_test], axis=1).astype("int")
    return LoadedDataset(
        "adult_adf", base.df, base.X_train, y_train, base.X_test, y_test,
        base.label, base.encoders, dict(base.notes, label_encoding="one-hot"),
    )


# ---------------------------------------------------------------------------
# Bank Marketing  (utils/verif_utils.py:309-366)
# ---------------------------------------------------------------------------

_BANK_COLUMNS = [
    "age", "job", "marital", "education", "default", "housing", "loan", "contact",
    "month", "day_of_week", "duration", "emp.var.rate", "campaign", "pdays",
    "previous", "poutcome", "y",
]


def load_bank(root=None) -> LoadedDataset:
    base = _root(root) / "bank"
    notes = {}
    path = base / "bank-additional-full.csv"
    if not path.is_file():  # LFS-missing in the reference checkout
        path = base / "bank-additional.csv"
        notes["data_file"] = "bank-additional.csv (full file unavailable)"
    df = pd.read_csv(path, sep=";", na_values=["unknown"]).dropna()

    df["age"] = (df["age"] >= 25).astype(float)  # binarized PA, verif_utils.py:325
    encoders: Dict[str, object] = {}
    for f in ["job", "marital", "education", "default", "housing", "loan",
              "contact", "month", "day_of_week", "poutcome"]:
        le = LabelEncoder()
        df[f] = le.fit_transform(df[f])
        encoders[f] = le

    df = df[_BANK_COLUMNS]
    label = "y"
    df[label] = np.where(df[label].isin(["yes"]), 1, 0)
    X = df.drop(columns=[label])
    y = df[label]
    X_train, y_train, X_test, y_test = _split(X, y)
    return LoadedDataset("bank", df, X_train, y_train, X_test, y_test, label, encoders, notes)


# ---------------------------------------------------------------------------
# Compas  (utils/verif_utils.py:243-265)
# ---------------------------------------------------------------------------


def load_compass(root=None) -> LoadedDataset:
    path = _root(root) / "compass" / "compas_preprocessed_full.csv"
    df = pd.read_csv(path)
    encoders: Dict[str, object] = {}
    for f in ["Two_yr_Recidivism", "Number_of_Priors", "Age", "Race", "Female", "Misdemeanor"]:
        le = LabelEncoder()
        df[f] = le.fit_transform(df[f])
        encoders[f] = le
    label = "score_factor"
    X = df.drop(columns=[label])
    y = df[label]
    X_train, y_train, X_test, y_test = _split(X, y)
    return LoadedDataset("compass", df, X_train, y_train, X_test, y_test, label, encoders)


def load_compass12(root=None) -> LoadedDataset:
    """Compas in the 12-feature encoding of ``data/compass/compass.csv``.

    The layout the reference's 12-input CP models consume (run only by its
    ``experimentData/task4`` notebooks; the committed driver sticks to the
    6-feature ``compas_preprocessed_full.csv``).  All columns arrive integer-
    encoded, so no further transformation is applied.
    """
    path = _root(root) / "compass" / "compass.csv"
    df = pd.read_csv(path)
    label = "label"
    X = df.drop(columns=[label])
    y = df[label]
    X_train, y_train, X_test, y_test = _split(X, y)
    return LoadedDataset("compass12", df, X_train, y_train, X_test, y_test, label, {})


# ---------------------------------------------------------------------------
# Default Credit  (utils/verif_utils.py:267-307)
# ---------------------------------------------------------------------------


def load_default(root=None) -> LoadedDataset:
    path = _root(root) / "default" / "default.csv"
    df = pd.read_csv(path)
    df = df.rename(columns={"PAY_0": "PAY_1"}).drop(columns=["ID"])

    cat_oh = ["SEX", "EDUCATION", "MARRIAGE"]
    oh = OneHotEncoder(drop="first", sparse_output=False)
    encoded = oh.fit_transform(df[cat_oh])
    encoded_df = pd.DataFrame(encoded, columns=oh.get_feature_names_out(cat_oh))
    df = df.drop(columns=cat_oh).reset_index(drop=True).join(encoded_df)

    mms_cols = ["PAY_1", "PAY_2", "PAY_3", "PAY_4", "PAY_5", "PAY_6"]
    mms = MinMaxScaler()
    df[mms_cols] = mms.fit_transform(df[mms_cols])

    label = "default.payment.next.month"
    X = df.drop(columns=[label])
    y = df[label]
    X_train, y_train, X_test, y_test = _split(X, y)
    encoders = {"onehot": oh, "minmax": mms}
    return LoadedDataset("default", df, X_train, y_train, X_test, y_test, label, encoders)


# ---------------------------------------------------------------------------
# Adult, one-hot 42-feature form  (utils/verif_utils.py:369-482; used by the
# experimentData notebooks rather than the main drivers)
# ---------------------------------------------------------------------------


def load_adult_onehot(root=None) -> LoadedDataset:
    base = _root(root) / "adult"
    train = pd.read_csv(base / "adult.data", header=None, names=_ADULT_COLUMNS,
                        skipinitialspace=True, na_values=["?"])
    test = pd.read_csv(base / "adult.test", header=0, names=_ADULT_COLUMNS,
                       skipinitialspace=True, na_values=["?"])
    df = pd.concat([test, train], ignore_index=True)

    for col in ["workclass", "occupation", "native-country"]:
        mode = df[col].mode(dropna=True)[0]
        df[col] = df[col].fillna(mode)

    df["education"] = df["education"].replace(
        {"11th": "HS-grad", "10th": "HS-grad", "9th": "HS-grad", "12th": "HS-grad"})
    df["education"] = df["education"].replace(
        {"1st-4th": "elementary_school", "5th-6th": "elementary_school", "7th-8th": "elementary_school"})
    df["marital-status"] = df["marital-status"].replace(
        {"Married-spouse-absent": "Married", "Married-civ-spouse": "Married", "Married-AF-spouse": "Married",
         "Separated": "Separated", "Divorced": "Separated"})
    df["workclass"] = df["workclass"].replace(
        {"Self-emp-not-inc": "Self_employed", "Self-emp-inc": "Self_employed",
         "Local-gov": "Govt_employees", "State-gov": "Govt_employees", "Federal-gov": "Govt_employees"})

    df = df.drop(columns=["education-num", "fnlwgt"]).dropna()
    df = pd.get_dummies(
        df, columns=["sex", "workclass", "education", "marital-status",
                     "occupation", "relationship", "native-country"], prefix_sep="=")
    le = LabelEncoder()
    df["race"] = le.fit_transform(df["race"])

    columns = [
        "education=Assoc-acdm", "education=Assoc-voc", "education=Bachelors",
        "education=Doctorate", "education=HS-grad", "education=Masters",
        "education=Preschool", "education=Prof-school", "education=elementary_school",
        "sex=Female", "marital-status=Married", "marital-status=Separated",
        "marital-status=Widowed", "occupation=Adm-clerical", "occupation=Armed-Forces",
        "occupation=Craft-repair", "occupation=Exec-managerial", "occupation=Farming-fishing",
        "occupation=Handlers-cleaners", "occupation=Machine-op-inspct",
        "occupation=Priv-house-serv", "occupation=Prof-specialty",
        "occupation=Protective-serv", "occupation=Sales", "occupation=Tech-support",
        "occupation=Transport-moving", "relationship=Husband", "relationship=Not-in-family",
        "relationship=Other-relative", "relationship=Own-child", "relationship=Unmarried",
        "relationship=Wife", "workclass=Govt_employees", "workclass=Never-worked",
        "workclass=Private", "workclass=Self_employed", "workclass=Without-pay",
        "race", "age", "capital-gain", "capital-loss", "hours-per-week", "income-per-year",
    ]
    df = df[[c for c in columns if c in df.columns]]
    label = "income-per-year"
    fav = df[label].isin([">50K", ">50K."])
    df[label] = np.where(fav, 1, 0)
    for c in df.columns:
        if df[c].dtype == bool:
            df[c] = df[c].astype(int)
    X = df.drop(columns=[label])
    y = df[label]
    X_train, y_train, X_test, y_test = _split(X, y)
    return LoadedDataset("adult_onehot", df, X_train, y_train, X_test, y_test, label, {"race": le})


# ---------------------------------------------------------------------------
# LSAC (Law School Admission Council bar-passage study).  The reference ships
# ``data/lsac/lsac.csv`` but no driver or loader ever reads it (SURVEY.md
# §2.4) — this loader + the ``lsac`` domain make the asset usable: a
# 9-feature integer-encodable subset (deciles, LSAT, UGPA×10, fulltime,
# family income, sex, race, school tier) with the standard bar-passage label.
# ---------------------------------------------------------------------------


def load_lsac(root=None) -> LoadedDataset:
    path = _root(root) / "lsac" / "lsac.csv"
    cols = ["decile1b", "decile3", "lsat", "ugpa", "fulltime", "fam_inc",
            "male", "race1", "tier"]
    label = "pass_bar"
    df = pd.read_csv(path)[cols + [label]].dropna().reset_index(drop=True)
    # UGPA is reported in tenths (1.5-3.9) and LSAT in half-points (e.g.
    # 14.5); scale both so the verification domain stays an integer lattice
    # (like every other dataset) without collapsing distinct raw values.
    df["ugpa"] = (df["ugpa"] * 10).round()
    df["lsat"] = (df["lsat"] * 2).round()
    le = LabelEncoder()
    df["race1"] = le.fit_transform(df["race1"])
    for c in df.columns:
        df[c] = df[c].astype(int)
    X = df.drop(columns=[label])
    y = df[label]
    X_train, y_train, X_test, y_test = _split(X, y)
    return LoadedDataset("lsac", df, X_train, y_train, X_test, y_test, label,
                         {"race1": le})


LOADERS = {
    "german": load_german,
    "adult": load_adult,
    "bank": load_bank,
    "compass": load_compass,
    "compass12": load_compass12,
    "default": load_default,
    "adult_onehot": load_adult_onehot,
    "adult_adf": load_adult_adf,
    "lsac": load_lsac,
}

_CACHE: Dict[str, LoadedDataset] = {}


def load(name: str, root=None, cache: bool = True) -> LoadedDataset:
    key = f"{name}:{root or DEFAULT_DATA_ROOT}"
    if cache and key in _CACHE:
        return _CACHE[key]
    ds = LOADERS[name](root)
    if cache:
        _CACHE[key] = ds
    return ds

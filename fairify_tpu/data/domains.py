"""Declarative attribute-domain specifications for every benchmark dataset.

The reference hard-codes each dataset's ``range_dict`` inside 21 near-identical
driver scripts (e.g. ``src/GC/Verify-GC.py:39-60``, ``src/AC/Verify-AC.py:45-58``,
``src/BM/Verify-BM.py:30-46``, ``src/CP/Verify-CP.py:47-53``,
``src/DF/Verify-DF.py:52-83``).  Here each domain is one declarative spec;
driver variants (stress/relaxed/targeted/targeted2) are config deltas in
:mod:`fairify_tpu.verify.presets`.

Attribute order matters: it must match the column order of the loaded
dataframe (minus the label), because counterexamples and constraints are
positional in the reference.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Tuple


@dataclass(frozen=True)
class DomainSpec:
    """Integer box domain of one tabular dataset plus its label metadata."""

    name: str
    ranges: Dict[str, Tuple[int, int]]
    label: str
    # Attributes for which the domain is an integer lattice (all reference
    # datasets; DF's scaled columns are still encoded as integers by the
    # driver, src/DF/Verify-DF.py:178-179).
    columns: Tuple[str, ...] = field(default=None)

    def __post_init__(self):
        if self.columns is None:
            object.__setattr__(self, "columns", tuple(self.ranges.keys()))

    def override(self, **ranges) -> "DomainSpec":
        """New spec with some attribute ranges replaced (targeted queries)."""
        new = dict(self.ranges)
        for k, v in ranges.items():
            if k not in new:
                raise KeyError(f"{self.name}: unknown attribute {k}")
            new[k] = tuple(v)
        return replace(self, ranges=new)

    def lo_hi(self):
        import numpy as np

        lo = np.array([self.ranges[c][0] for c in self.columns], dtype=np.float32)
        hi = np.array([self.ranges[c][1] for c in self.columns], dtype=np.float32)
        return lo, hi


# German Credit — src/GC/Verify-GC.py:39-60 (20 features, label 'credit').
GERMAN = DomainSpec(
    name="german",
    label="credit",
    ranges={
        "status": (0, 2),
        "month": (0, 80),
        "credit_history": (0, 2),
        "purpose": (0, 9),
        "credit_amount": (0, 20000),
        "savings": (0, 2),
        "employment": (0, 2),
        "investment_as_income_percentage": (1, 4),
        "other_debtors": (0, 2),
        "residence_since": (1, 4),
        "property": (0, 2),
        "age": (0, 1),
        "installment_plans": (0, 2),
        "housing": (0, 2),
        "number_of_credits": (1, 4),
        "skill_level": (0, 3),
        "people_liable_for": (1, 2),
        "telephone": (0, 1),
        "foreign_worker": (0, 1),
        "sex": (0, 1),
    },
)

# Adult Census — src/AC/Verify-AC.py:45-58 (13 features, label 'income-per-year').
ADULT = DomainSpec(
    name="adult",
    label="income-per-year",
    ranges={
        "age": (10, 100),
        "workclass": (0, 6),
        "education": (0, 15),
        "education-num": (1, 16),
        "marital-status": (0, 6),
        "occupation": (0, 13),
        "relationship": (0, 5),
        "race": (0, 4),
        "sex": (0, 1),
        "capital-gain": (0, 19),
        "capital-loss": (0, 19),
        "hours-per-week": (1, 100),
        "native-country": (0, 40),
    },
)

# Bank Marketing — src/BM/Verify-BM.py:30-46 (16 features, label 'y').
BANK = DomainSpec(
    name="bank",
    label="y",
    ranges={
        "age": (0, 1),
        "job": (0, 10),
        "marital": (0, 2),
        "education": (0, 6),
        "default": (0, 1),
        "housing": (0, 1),
        "loan": (0, 1),
        "contact": (0, 1),
        "month": (0, 11),
        "day_of_week": (0, 6),
        "duration": (0, 5000),
        "emp.var.rate": (-3, 1),
        "campaign": (1, 50),
        "pdays": (0, 999),
        "previous": (0, 7),
        "poutcome": (0, 2),
    },
)

# Compas — src/CP/Verify-CP.py:47-53 (6 features, label 'score_factor').
COMPAS = DomainSpec(
    name="compass",
    label="score_factor",
    ranges={
        "Two_yr_Recidivism": (0, 1),
        "Number_of_Priors": (0, 38),
        "Age": (0, 1),
        "Race": (0, 1),
        "Female": (0, 1),
        "Misdemeanor": (0, 1),
    },
)

# Compas, 12-feature encoding (``data/compass/compass.csv``) — the input
# layout of the reference's CP-2..10 / aCP-1-Old zoo models, which its
# committed CP driver never runs (it filters to CP-11,
# ``src/CP/Verify-CP.py:91``; the 12-input family is exercised only by the
# ``experimentData/task4`` node runs).  Ranges profiled from the CSV; the
# anonymized columns d..l are small ordinal scores.
COMPAS12 = DomainSpec(
    name="compass12",
    label="label",
    ranges={
        "sex": (0, 1),
        "age": (0, 2),
        "race": (0, 1),
        "d": (0, 20),
        "e": (1, 10),
        "f": (0, 38),
        "g": (0, 1),
        "h": (0, 1),
        "i": (0, 1),
        "j": (1, 10),
        "k": (1, 10),
        "l": (0, 38),
    },
)

# Default Credit — src/DF/Verify-DF.py:52-83 (30 features).
DEFAULT_CREDIT = DomainSpec(
    name="default",
    label="default.payment.next.month",
    ranges={
        "LIMIT_BAL": (10000, 1000000),
        "AGE": (21, 79),
        "PAY_1": (0, 1),
        "PAY_2": (0, 1),
        "PAY_3": (0, 1),
        "PAY_4": (0, 1),
        "PAY_5": (0, 1),
        "PAY_6": (0, 1),
        "BILL_AMT1": (-165580, 964511),
        "BILL_AMT2": (-69777, 983931),
        "BILL_AMT3": (-157264, 1664089),
        "BILL_AMT4": (-170000, 891586),
        "BILL_AMT5": (-81334, 927171),
        "BILL_AMT6": (-339603, 961664),
        "PAY_AMT1": (0, 873552),
        "PAY_AMT2": (0, 1684259),
        "PAY_AMT3": (0, 896040),
        "PAY_AMT4": (0, 621000),
        "PAY_AMT5": (0, 426529),
        "PAY_AMT6": (0, 528666),
        "SEX_2": (0, 1),
        "EDUCATION_1": (0, 1),
        "EDUCATION_2": (0, 1),
        "EDUCATION_3": (0, 1),
        "EDUCATION_4": (0, 1),
        "EDUCATION_5": (0, 1),
        "EDUCATION_6": (0, 1),
        "MARRIAGE_1": (0, 1),
        "MARRIAGE_2": (0, 1),
        "MARRIAGE_3": (0, 1),
    },
)

# LSAC bar passage — asset shipped but never wired up by the reference
# (``data/lsac``, SURVEY.md §2.4); ranges match ``loaders.load_lsac``'s
# integer encoding (UGPA in tenths, LSAT in half-points ×2, race1
# label-encoded alphabetically).
LSAC = DomainSpec(
    name="lsac",
    label="pass_bar",
    ranges={
        "decile1b": (1, 10),
        "decile3": (1, 10),
        "lsat": (22, 96),
        "ugpa": (15, 39),
        "fulltime": (1, 2),
        "fam_inc": (1, 5),
        "male": (0, 1),
        "race1": (0, 4),
        "tier": (1, 6),
    },
)

DOMAINS = {
    "german": GERMAN,
    "adult": ADULT,
    "bank": BANK,
    "compass": COMPAS,
    "compass12": COMPAS12,
    "default": DEFAULT_CREDIT,
    "lsac": LSAC,
}


def get_domain(name: str) -> DomainSpec:
    return DOMAINS[name]

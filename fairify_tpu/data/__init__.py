from fairify_tpu.data.domains import DOMAINS, DomainSpec, get_domain

__all__ = ["DOMAINS", "DomainSpec", "get_domain"]

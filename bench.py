"""Headline benchmark: verified partitions/sec on the GC-1 German sweep.

Reference baseline (BASELINE.md, Appendix Table V, GC1/Age): 46 partitions
attempted in the 30-minute budget at a mean 43.19 s/partition on CPU —
0.02315 verified partitions/sec.  This benchmark runs the same query
(German Credit, PA=age, partition threshold 100 → 201 partitions) through
the TPU-native engine end-to-end (sound pruning, stage-0 certificates +
attack, branch-and-bound refinement) and reports decided partitions/sec.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""
from __future__ import annotations

import json
import sys
import time

REFERENCE_PARTITIONS_PER_SEC = 46 / (46 * 43.19)  # GC1/Age, Table V
# Reference per-family decided-partition rates (BASELINE.md Table V, mean
# s/part over a family's rows → partitions/sec on the reference CPU):
REF_PPS_AC = 0.00917   # 24 AC rows, mean 109.05 s/part
REF_PPS_BM = 0.0398    # 8 BM rows, mean 25.13 s/part


def _probe_ok() -> bool:
    """Probe the default jax backend in a subprocess with a timeout.

    The tunnelled TPU platform hangs (rather than errors) when its relay is
    down; a hung benchmark is worse than a CPU number, so the probe gets 60s
    and main() re-execs under a forced-CPU environment on failure.
    """
    import os
    import subprocess

    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=60, capture_output=True, check=True,
        )
        return True
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        return False


def main(trace_out=None, heartbeat_s: float = 0.0) -> None:
    import os

    if not os.environ.get("FAIRIFY_TPU_BENCH_FALLBACK") and not _probe_ok():
        env = dict(os.environ, FAIRIFY_TPU_BENCH_FALLBACK="1", PYTHONPATH="",
                   JAX_PLATFORMS="cpu")
        import subprocess

        cmd = [sys.executable, __file__]
        if trace_out:
            cmd += ["--trace-out", trace_out]
        if heartbeat_s:
            cmd += ["--heartbeat-interval", str(heartbeat_s)]
        raise SystemExit(subprocess.run(cmd, env=env).returncode)

    import numpy as np

    from fairify_tpu.verify import engine, presets, sweep
    from __graft_entry__ import _flagship_net

    cfg = presets.get("GC").with_(
        result_dir="/tmp/fairify_tpu_bench",
        soft_timeout_s=10.0,
        hard_timeout_s=10 * 60.0,
        exact_certify_masks=False,  # parity pass off for the timing run
        engine=engine.EngineConfig(frontier_size=512, attack_samples=128,
                                   bab_attack_samples=16, soft_timeout_s=10.0),
    )
    net = _flagship_net()

    import shutil

    shutil.rmtree("/tmp/fairify_tpu_bench", ignore_errors=True)
    # Warm-up: ONE FULL untimed run of the exact headline sweep.  The r4
    # regression (BENCH_r04 25.96 vs r3 54.73 parts/s) was cold-process
    # compiles/traces of the round-4 phase-ladder kernels landing inside the
    # timed region — a stage-0-only warmup misses the PGD scan+grad, sign-BaB
    # and LP-phase kernels.  Running the identical sweep once compiles every
    # kernel at its exact shapes; the timed run then measures the engine, not
    # the tracer (VERDICT r5 #1).
    warm = cfg.with_(result_dir="/tmp/fairify_tpu_bench_warm")
    shutil.rmtree("/tmp/fairify_tpu_bench_warm", ignore_errors=True)
    try:
        sweep.verify_model(net, warm, model_name="warmup", resume=False)
    except Exception as exc:
        print(json.dumps({"metric": "warmup_error", "error": str(exc)[:200]}),
              file=sys.stderr)

    # --- Promotion-ladder configs (BASELINE.json "configs"): one JSON line
    # each, printed BEFORE the headline (the driver parses the last line).
    try:
        _ladder_configs()
    except Exception as exc:  # a ladder failure must never kill the headline
        print(json.dumps({"metric": "ladder_error", "error": str(exc)[:200]}),
              file=sys.stderr)

    from fairify_tpu import obs

    if heartbeat_s:
        cfg = cfg.with_(heartbeat_s=heartbeat_s)
    t0 = time.perf_counter()
    # Tracer scope covers only the timed headline run (the warm pass above
    # must not pollute the event log's phase totals).
    with obs.tracing(trace_out, run_id="bench-GC-1"):
        report = sweep.verify_model(net, cfg, model_name="GC-1", resume=False)
    elapsed = time.perf_counter() - t0

    # Per-run observability summary for the BENCH record: the sweep's
    # throughput dump carries the phase breakdown and the launch delta, so
    # future BENCH_r*.json rounds can regress launch economy and per-phase
    # wall time alongside partitions/sec.
    launches = None
    phases_s = None
    try:
        with open(os.path.join(cfg.result_dir,
                               f"{cfg.name}-GC-1.throughput.json")) as fp:
            thr = json.load(fp)
        launches = thr.get("device_launches")
        phases_s = thr.get("phases_s")
    except (OSError, ValueError):
        pass

    counts = report.counts
    decided = counts["sat"] + counts["unsat"]
    pps = decided / elapsed if elapsed > 0 else 0.0
    print(json.dumps({
        "metric": "verified_partitions_per_sec_per_chip (GC-1, PA=age, 201 partitions; "
                  f"sat={counts['sat']} unsat={counts['unsat']} unk={counts['unknown']})",
        "value": round(pps, 4),
        "unit": "partitions/sec",
        "vs_baseline": round(pps / REFERENCE_PARTITIONS_PER_SEC, 2),
        "device_launches": launches,
        "phases_s": phases_s,
    }))


def _ladder_configs() -> None:
    """The remaining BASELINE.json ladder configs, one JSON line each.

    * AC suite — the 12 shipped adult models as ONE stacked pytree, stage-0
      certify+attack vmapped over the model axis on the full 16k grid
      (``sweep._stage0_family``); metric = stage-0-decided
      model-partitions/sec (the suite's dominant kernel).
    * stress-BM / relaxed-AC — 60 s budgeted prefixes at reference
      attempt-until-budget semantics (``_sweeplib.budgeted_model_sweep``).

    vs_baseline uses the family's mean Table V s/part (the reference has no
    published stress/relaxed tables; its base-family CPU rate is the
    closest like-for-like denominator, noted in the metric strings).
    """
    import os

    import numpy as np

    from fairify_tpu.models import zoo
    from fairify_tpu.parallel.mesh import stack_models
    from fairify_tpu.verify import presets, sweep
    from fairify_tpu.verify.property import encode

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "scripts"))
    from _sweeplib import budgeted_model_sweep

    # AC 12-model vmap suite (stacked per architecture group, the same
    # grouping run_sweep uses — the zoo's AC nets span several depths).
    cfg = presets.get("AC").with_(result_dir="/tmp/fairify_tpu_bench_ac")
    nets, _ = zoo.load_matching("adult", len(cfg.query().columns))
    names = sorted(nets)
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)
    from collections import defaultdict

    groups = defaultdict(list)
    for n in names:
        groups[(nets[n].in_dim,) + nets[n].layer_sizes].append(n)
    stacks = [stack_models([nets[n] for n in g]) for g in groups.values()]
    for st in stacks:  # warm/compile pass per architecture
        sweep._stage0_family(st, enc, lo[:2048], hi[:2048], cfg)
    t0 = time.perf_counter()
    decided = 0
    for st in stacks:
        fam = sweep._stage0_family(st, enc, lo, hi, cfg)
        decided += int(sum((u | s).sum() for u, s, _ in fam))
    dt = time.perf_counter() - t0
    pps = decided / dt
    print(json.dumps({
        "metric": f"ac_suite_vmap_stage0_decided_model_partitions_per_sec "
                  f"({len(names)} adult models x {lo.shape[0]} partitions, "
                  f"decided {decided}; baseline = Table V AC mean s/part)",
        "value": round(pps, 1),
        "unit": "model-partitions/sec",
        "vs_baseline": round(pps / REF_PPS_AC, 1),
    }), flush=True)

    # Budgeted variant prefixes (stress-BM mesh-analog + relaxed-eps).
    # Each config runs TWICE: one full untimed warm pass (identical config,
    # so every kernel the timed pass will launch is compiled at its exact
    # shapes), then the timed pass — same warm-vs-timed discipline as the
    # headline (VERDICT r5 #1: the r4 stress/relaxed collapse was compiles
    # inside the 60 s budget).
    import shutil

    for preset, model, ref_pps in (("stress-BM", "BM-1", REF_PPS_BM),
                                   ("relaxed-AC", "AC-1", REF_PPS_AC)):
        vcfg = presets.get(preset).with_(
            soft_timeout_s=100.0, hard_timeout_s=60.0,
            result_dir=f"/tmp/fairify_tpu_bench_{preset}")
        net = zoo.load(vcfg.dataset, model)
        shutil.rmtree(vcfg.result_dir, ignore_errors=True)
        budgeted_model_sweep(vcfg, net, model)  # warm (untimed)
        shutil.rmtree(vcfg.result_dir, ignore_errors=True)
        row = budgeted_model_sweep(vcfg, net, model)
        print(json.dumps({
            "metric": f"{preset}_budgeted_decided_partitions_per_sec "
                      f"({model}, 60s budget, wall {row['total_time_s']}s, "
                      f"attempted {row['attempted']} "
                      f"of {row['partitions']}, unk {row['unknown']}; "
                      f"baseline = Table V family mean s/part)",
            "value": row["decided_per_sec"],
            "unit": "partitions/sec",
            "vs_baseline": round(row["decided_per_sec"] / ref_pps, 1),
        }), flush=True)


if __name__ == "__main__":
    import argparse

    _ap = argparse.ArgumentParser()
    _ap.add_argument("--trace-out", default=None)
    _ap.add_argument("--heartbeat-interval", type=float, default=0.0)
    _a = _ap.parse_args()
    sys.exit(main(trace_out=_a.trace_out, heartbeat_s=_a.heartbeat_interval))

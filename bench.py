"""Headline benchmark: verified partitions/sec on the GC-1 German sweep.

Reference baseline (BASELINE.md, Appendix Table V, GC1/Age): 46 partitions
attempted in the 30-minute budget at a mean 43.19 s/partition on CPU —
0.02315 verified partitions/sec.  This benchmark runs the same query
(German Credit, PA=age, partition threshold 100 → 201 partitions) through
the TPU-native engine end-to-end (sound pruning, stage-0 certificates +
attack, branch-and-bound refinement) and reports decided partitions/sec.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""
from __future__ import annotations

import contextlib
import json
import shutil
import sys
import time

# Timed repeats per config (VERDICT r5 #7): odd, so the median is a run.
BENCH_REPEATS = 3


def _median_band(runs):
    """(median, min, max) over the repeats' ``value`` fields — the one
    statistic every ladder line quotes (odd repeat count: the median IS a
    run, so per-run detail fields can be looked up by value)."""
    vals = sorted(r["value"] for r in runs)
    return vals[len(vals) // 2], vals[0], vals[-1]

REFERENCE_PARTITIONS_PER_SEC = 46 / (46 * 43.19)  # GC1/Age, Table V
# Reference per-family decided-partition rates (BASELINE.md Table V, mean
# s/part over a family's rows → partitions/sec on the reference CPU):
REF_PPS_AC = 0.00917   # 24 AC rows, mean 109.05 s/part
REF_PPS_BM = 0.0398    # 8 BM rows, mean 25.13 s/part


def _probe_ok() -> bool:
    """Probe the default jax backend in a subprocess with a timeout.

    The tunnelled TPU platform hangs (rather than errors) when its relay is
    down; a hung benchmark is worse than a CPU number, so the probe gets 60s
    and main() re-execs under a forced-CPU environment on failure.
    """
    import os
    import subprocess

    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=60, capture_output=True, check=True,
        )
        return True
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        return False


def main(trace_out=None, heartbeat_s: float = 0.0, xprof_dir=None) -> None:
    import os

    if not os.environ.get("FAIRIFY_TPU_BENCH_FALLBACK") and not _probe_ok():
        env = dict(os.environ, FAIRIFY_TPU_BENCH_FALLBACK="1", PYTHONPATH="",
                   JAX_PLATFORMS="cpu")
        import subprocess

        cmd = [sys.executable, __file__]
        if trace_out:
            cmd += ["--trace-out", trace_out]
        if heartbeat_s:
            cmd += ["--heartbeat-interval", str(heartbeat_s)]
        if xprof_dir:
            cmd += ["--xprof-dir", xprof_dir]
        raise SystemExit(subprocess.run(cmd, env=env).returncode)

    import numpy as np

    from fairify_tpu.verify import engine, presets, sweep
    from __graft_entry__ import _flagship_net

    cfg = presets.get("GC").with_(
        result_dir="/tmp/fairify_tpu_bench",
        soft_timeout_s=10.0,
        hard_timeout_s=10 * 60.0,
        exact_certify_masks=False,  # parity pass off for the timing run
        engine=engine.EngineConfig(frontier_size=512, attack_samples=128,
                                   bab_attack_samples=16, soft_timeout_s=10.0),
    )
    net = _flagship_net()

    shutil.rmtree("/tmp/fairify_tpu_bench", ignore_errors=True)
    # Warm-up: ONE FULL untimed run of the exact headline sweep.  The r4
    # regression (BENCH_r04 25.96 vs r3 54.73 parts/s) was cold-process
    # compiles/traces of the round-4 phase-ladder kernels landing inside the
    # timed region — a stage-0-only warmup misses the PGD scan+grad, sign-BaB
    # and LP-phase kernels.  Running the identical sweep once compiles every
    # kernel at its exact shapes; the timed run then measures the engine, not
    # the tracer (VERDICT r5 #1).
    warm = cfg.with_(result_dir="/tmp/fairify_tpu_bench_warm")
    shutil.rmtree("/tmp/fairify_tpu_bench_warm", ignore_errors=True)
    from fairify_tpu.obs import compile as compile_obs

    compile_pre_warm = compile_obs.snapshot_totals()
    try:
        sweep.verify_model(net, warm, model_name="warmup", resume=False)
    except Exception as exc:
        print(json.dumps({"metric": "warmup_error", "error": str(exc)[:200]}),
              file=sys.stderr)
    # The compile split (obs.compile): the warm-up pass eats the cold
    # XLA compiles; the timed repeats report their residual compile_s so a
    # nonzero value there is itself a regression signal (shape churn the
    # warm-up should have covered).
    warm_compile = compile_obs.totals_delta(compile_pre_warm)

    # --- Promotion-ladder configs (BASELINE.json "configs"): one JSON line
    # each, printed BEFORE the headline (the driver parses the last line).
    try:
        _ladder_configs()
    except Exception as exc:  # a ladder failure must never kill the headline
        print(json.dumps({"metric": "ladder_error", "error": str(exc)[:200]}),
              file=sys.stderr)

    from fairify_tpu import obs

    if heartbeat_s:
        cfg = cfg.with_(heartbeat_s=heartbeat_s)

    # Variance discipline (VERDICT r5 #7): ≥3 timed repeats of the identical
    # headline sweep; the quoted number is the MEDIAN, with min/max and the
    # per-repeat records in ``runs`` so BENCH_r*.json rounds carry the noise
    # band a single-shot number hides.  The metrics registry is reset
    # between repeats so each repeat's device_launches delta (and the
    # in-flight gauge) is per-run, not cumulative.  Only the last repeat is
    # traced: one run per event log keeps the report's phase totals honest.
    runs = []
    report = None
    for rep_i in range(BENCH_REPEATS):
        shutil.rmtree(cfg.result_dir, ignore_errors=True)
        obs.registry().reset()
        t0 = time.perf_counter()
        # Only the LAST repeat is traced (obs + XProf): one run per event
        # log keeps the report's phase totals honest, and one profiler
        # capture keeps the XProf artifact small enough to load.
        last = rep_i == BENCH_REPEATS - 1
        tracing = obs.tracing(trace_out, run_id="bench-GC-1") \
            if last else contextlib.nullcontext()
        from fairify_tpu.utils import profiling as profiling_mod

        with tracing, profiling_mod.xla_trace(xprof_dir if last else None):
            rep = sweep.verify_model(net, cfg, model_name="GC-1", resume=False)
        elapsed = time.perf_counter() - t0
        if report is not None and rep.counts != report.counts:
            print(json.dumps({"metric": "repeat_verdict_drift",
                              "run": rep_i, "counts": rep.counts}),
                  file=sys.stderr)
        report = rep
        decided = rep.counts["sat"] + rep.counts["unsat"]
        run_rec = {"value": round(decided / elapsed, 4) if elapsed > 0 else 0.0,
                   "elapsed_s": round(elapsed, 3)}
        # The sweep's throughput dump carries the phase breakdown, the
        # launch delta and the async-pipeline overlap gauge per repeat.
        try:
            with open(os.path.join(cfg.result_dir,
                                   f"{cfg.name}-GC-1.throughput.json")) as fp:
                thr = json.load(fp)
            run_rec["device_launches"] = thr.get("device_launches")
            run_rec["launches_per_model"] = thr.get("launches_per_model")
            run_rec["phases_s"] = thr.get("phases_s")
            run_rec["pipeline_depth"] = thr.get("pipeline_depth")
            run_rec["launches_in_flight_max"] = thr.get("launches_in_flight_max")
            run_rec["launches_in_flight_mean"] = thr.get("launches_in_flight_mean")
            run_rec["compile_s"] = thr.get("compile_s")
            run_rec["n_compiles"] = thr.get("n_compiles")
            run_rec["decided_fraction"] = thr.get("decided_fraction")
            res = thr.get("resilience") or {}
            run_rec["integrity_violations"] = res.get(
                "integrity_violations", 0)
            run_rec["ledger_crc_mismatch"] = res.get(
                "ledger_crc_mismatch", 0)
        except (OSError, ValueError):
            pass
        runs.append(run_rec)

    pps, lo_v, hi_v = _median_band(runs)

    # Integrity-recheck A/B (ISSUE 19, DESIGN.md §21): ONE extra run with
    # the benched sampled-recheck rate; overhead_rel is the decided-
    # throughput cost vs the plain median — perfdiff gates it lower-is-
    # better with a 5-point floor, so a recheck that stops being
    # within-noise fails the round.
    from fairify_tpu.resilience import integrity as integrity_mod

    integrity_ab = None
    try:
        shutil.rmtree(cfg.result_dir, ignore_errors=True)
        obs.registry().reset()
        rcfg = cfg.with_(
            integrity_recheck=integrity_mod.DEFAULT_RECHECK_RATE)
        t0 = time.perf_counter()
        rrep = sweep.verify_model(net, rcfg, model_name="GC-1",
                                  resume=False)
        relapsed = time.perf_counter() - t0
        rdecided = rrep.counts["sat"] + rrep.counts["unsat"]
        pps_on = round(rdecided / relapsed, 4) if relapsed > 0 else 0.0
        integrity_ab = {
            "recheck_rate": integrity_mod.DEFAULT_RECHECK_RATE,
            "pps_on": pps_on,
            "pps_off": pps,
            "overhead_rel": (round(max(0.0, (pps - pps_on) / pps), 4)
                             if pps > 0 else 0.0),
            "rechecks": int(obs.registry().counter(
                "integrity_rechecks").total()),
            "violations": int(obs.registry().counter(
                "integrity_violations").total()),
        }
    except Exception as exc:  # the A/B must never kill the headline
        print(json.dumps({"metric": "integrity_ab_error",
                          "error": str(exc)[:200]}), file=sys.stderr)
    counts = report.counts
    median_run = next(r for r in runs if r["value"] == pps)
    print(json.dumps({
        "metric": "verified_partitions_per_sec_per_chip (GC-1, PA=age, 201 partitions; "
                  f"sat={counts['sat']} unsat={counts['unsat']} unk={counts['unknown']}; "
                  f"median of {len(runs)} repeats)",
        "value": pps,
        "unit": "partitions/sec",
        "vs_baseline": round(pps / REFERENCE_PARTITIONS_PER_SEC, 2),
        "min": lo_v,
        "max": hi_v,
        "runs": runs,
        "device_launches": median_run.get("device_launches"),
        # Launch economy (perfdiff-gated, lower is better): launches per
        # model — O(segments) under the stage-0 mega-loop.
        "launches_per_model": median_run.get("launches_per_model"),
        "phases_s": median_run.get("phases_s"),
        "pipeline_depth": median_run.get("pipeline_depth"),
        "launches_in_flight_max": median_run.get("launches_in_flight_max"),
        # Compile split: the warm-up run absorbed the cold XLA compiles
        # (reported here, outside the timed medians); the median timed
        # repeat's residual compile_s should be ~0 on a healthy run.
        "warmup_compile_s": round(warm_compile["compile_s"], 3),
        "warmup_n_compiles": warm_compile["n_compiles"],
        "compile_s": median_run.get("compile_s"),
        "n_compiles": median_run.get("n_compiles"),
        # Funnel success metric (obs.funnel, perfdiff-gated HIGHER is
        # better): decided partitions over classified partitions.
        "decided_fraction": median_run.get("decided_fraction"),
        # Integrity (DESIGN.md §21, perfdiff-gated lower is better): both
        # counters must stay zero on a healthy bench; integrity_ab carries
        # the sampled-recheck overhead vs the plain median.
        "integrity_violations": median_run.get("integrity_violations"),
        "ledger_crc_mismatch": median_run.get("ledger_crc_mismatch"),
        "integrity_ab": integrity_ab,
    }))


def _ladder_configs() -> None:
    """The remaining BASELINE.json ladder configs, one JSON line each.

    * AC suite — the 12 shipped adult models as ONE stacked pytree, stage-0
      certify+attack vmapped over the model axis on the full 16k grid
      (``sweep._stage0_family``); metric = stage-0-decided
      model-partitions/sec (the suite's dominant kernel).
    * stress-BM / relaxed-AC — 60 s budgeted prefixes at reference
      attempt-until-budget semantics (``_sweeplib.budgeted_model_sweep``).

    vs_baseline uses the family's mean Table V s/part (the reference has no
    published stress/relaxed tables; its base-family CPU rate is the
    closest like-for-like denominator, noted in the metric strings).
    """
    import os

    import numpy as np

    from fairify_tpu.models import zoo
    from fairify_tpu.parallel.mesh import stack_models
    from fairify_tpu.verify import presets, sweep
    from fairify_tpu.verify.property import encode

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "scripts"))
    from _sweeplib import budgeted_model_sweep

    # Device-BaB ladder first: it is zoo-free (synthetic world), so it
    # records even on bare containers where the AC/stress ladders skip.
    try:
        _bab_ladder()
    except Exception as exc:
        print(json.dumps({"metric": "bab_ladder_error",
                          "error": str(exc)[:200]}), file=sys.stderr)

    # AC 12-model vmap suite (stacked per architecture group, the same
    # grouping run_sweep uses — the zoo's AC nets span several depths).
    cfg = presets.get("AC").with_(result_dir="/tmp/fairify_tpu_bench_ac")
    try:
        nets, _ = zoo.load_matching("adult", len(cfg.query().columns))
    except OSError:
        nets = {}
    names = sorted(nets)
    if not names:
        # Reference zoo assets absent (bare container): emitting a zero
        # metric would gate future rounds against a meaningless baseline —
        # skip the ladder loudly instead (the headline uses the synthetic
        # flagship twin and still records).
        print(json.dumps({"metric": "ladder_skipped",
                          "error": "no adult zoo models on this host"}),
              file=sys.stderr)
        return
    enc = encode(cfg.query())
    _, lo, hi = sweep.build_partitions(cfg)
    from collections import defaultdict

    groups = defaultdict(list)
    for n in names:
        groups[(nets[n].in_dim,) + nets[n].layer_sizes].append(n)
    stacks = [stack_models([nets[n] for n in g]) for g in groups.values()]
    for st in stacks:  # warm/compile pass per architecture
        sweep._stage0_family(st, enc, lo[:2048], hi[:2048], cfg)
    # Timed repeats: every (architecture, chunk) block of all stacks rides
    # ONE shared async pipeline (sweep.stage0_families), so the device
    # queue never drains between the suite's families; the per-repeat
    # in-flight stats land in the runs records.
    from fairify_tpu import obs
    from fairify_tpu.parallel.pipeline import LaunchPipeline

    from fairify_tpu.utils import profiling

    ac_runs = []
    decided = 0
    for _ in range(BENCH_REPEATS):
        obs.registry().reset()
        pipe = LaunchPipeline(cfg.pipeline_depth)
        launch0 = profiling.launch_count()
        t0 = time.perf_counter()
        fams = sweep.stage0_families(stacks, enc, lo, hi, cfg, pipe=pipe)
        dt = time.perf_counter() - t0
        launches = profiling.launch_count() - launch0
        decided = int(sum((u | s).sum() for fam in fams for u, s, _ in fam))
        ac_runs.append({"value": round(decided / dt, 1),
                        "decided_fraction": round(
                            decided / (len(names) * lo.shape[0]), 6),
                        "elapsed_s": round(dt, 3),
                        "device_launches": launches,
                        "launches_per_model": round(
                            launches / max(len(names), 1), 2),
                        "launches_in_flight_max": pipe.stats.max,
                        "launches_in_flight_mean": round(pipe.stats.mean(), 3)})
    pps, lo_v, hi_v = _median_band(ac_runs)
    print(json.dumps({
        "metric": f"ac_suite_vmap_stage0_decided_model_partitions_per_sec "
                  f"({len(names)} adult models x {lo.shape[0]} partitions, "
                  f"decided {decided}; median of {len(ac_runs)} repeats; "
                  f"baseline = Table V AC mean s/part)",
        "value": pps,
        "unit": "model-partitions/sec",
        "vs_baseline": round(pps / REF_PPS_AC, 1),
        "min": lo_v,
        "max": hi_v,
        "runs": ac_runs,
        "decided_fraction": ac_runs[-1]["decided_fraction"],
        "pipeline_depth": cfg.pipeline_depth,
        "device_launches": ac_runs[-1]["device_launches"],
        "launches_per_model": ac_runs[-1]["launches_per_model"],
        "launches_in_flight_max": max(r["launches_in_flight_max"]
                                      for r in ac_runs),
    }), flush=True)

    # Budgeted variant prefixes (stress-BM mesh-analog + relaxed-eps).
    # Each config runs one full untimed warm pass (identical config, so
    # every kernel the timed passes will launch is compiled at its exact
    # shapes), then ≥3 timed repeats — same warm-vs-timed discipline as the
    # headline (VERDICT r5 #1: the r4 stress/relaxed collapse was compiles
    # inside the 60 s budget), with the result dir and metrics registry
    # reset between repeats so no repeat resumes past another's ledgers.
    for preset, model, ref_pps in (("stress-BM", "BM-1", REF_PPS_BM),
                                   ("relaxed-AC", "AC-1", REF_PPS_AC)):
        vcfg = presets.get(preset).with_(
            soft_timeout_s=100.0, hard_timeout_s=60.0,
            result_dir=f"/tmp/fairify_tpu_bench_{preset}")
        net = zoo.load(vcfg.dataset, model)
        shutil.rmtree(vcfg.result_dir, ignore_errors=True)
        budgeted_model_sweep(vcfg, net, model)  # warm (untimed)
        b_runs = []
        row = None
        for _ in range(BENCH_REPEATS):
            shutil.rmtree(vcfg.result_dir, ignore_errors=True)
            obs.registry().reset()
            row = budgeted_model_sweep(vcfg, net, model)
            b_runs.append({"value": row["decided_per_sec"],
                           "elapsed_s": row["total_time_s"],
                           "attempted": row["attempted"],
                           "unknown": row["unknown"],
                           "decided_fraction": row["decided_fraction"]})
        pps, lo_v, hi_v = _median_band(b_runs)
        print(json.dumps({
            "metric": f"{preset}_budgeted_decided_partitions_per_sec "
                      f"({model}, 60s budget, wall {row['total_time_s']}s, "
                      f"attempted {row['attempted']} "
                      f"of {row['partitions']}, unk {row['unknown']}; "
                      f"median of {len(b_runs)} repeats; "
                      f"baseline = Table V family mean s/part)",
            "value": pps,
            "unit": "partitions/sec",
            "vs_baseline": round(pps / ref_pps, 1),
            "min": lo_v,
            "max": hi_v,
            "runs": b_runs,
            # Over the FULL grid: the unattempted tail counts against the
            # fraction as unknown:budget (reference Cov% semantics).
            "decided_fraction": row["decided_fraction"],
        }), flush=True)


def _bab_ladder() -> None:
    """Device-BaB budgeted ladder (DESIGN.md §22) — zoo-free by design.

    A synthetic German-derived world whose every partition survives
    stage-0 and the pre-BaB phase ladder, so the engine BaB decides the
    whole grid: the sharpest available probe of the device-resident
    frontier's launch economy.  One line, device queue ON (the shipped
    default); the ``bab_ab`` block carries the host-frontier control at
    the identical budget.  On the tunnelled single-chip setup every launch
    pays the ~110 ms relay round-trip (audits/device_util_r4.json), so
    ``launches_per_partition`` — O(segments) for the device queue vs
    O(rounds x CROWN batches) for the host loop — is the governing,
    deterministic metric; on a local CPU backend the wall-clock gap is
    launch-overhead-free and correspondingly smaller.  perfdiff gates
    ``decided_fraction`` higher-is-better and the launch counters
    lower-is-better once a baseline round carries this line.
    """
    import numpy as np  # noqa: F401  (parity with sibling ladders)

    from fairify_tpu import obs
    from fairify_tpu.data.domains import get_domain
    from fairify_tpu.models.train import init_mlp
    from fairify_tpu.utils import profiling
    from fairify_tpu.verify import engine as engine_mod
    from fairify_tpu.verify import presets, sweep

    ov = {c: (0, 0) for c in get_domain("german").columns}
    ov.update(age=(0, 1), month=(0, 5), purpose=(0, 5), credit_amount=(0, 2))
    eng = engine_mod.EngineConfig(
        pgd_phase=False, sign_bab=False, lp_sign=False, lp_pair=False,
        lattice_exhaustive=False, attack_samples=4, bab_attack_samples=4,
        bab_frontier_cap=64, bab_rounds_per_segment=8)
    n_parts = 8
    rows = {}
    for mode in ("device", "host"):
        cfg = presets.get("GC").with_(
            result_dir=f"/tmp/fairify_tpu_bench_bab_{mode}",
            soft_timeout_s=20.0, hard_timeout_s=120.0, sim_size=16,
            exact_certify_masks=False, grid_chunk=8, domain_overrides=ov,
            partition_threshold=2, device_bab=(mode == "device"), engine=eng)
        net = init_mlp((len(cfg.query().columns), 4, 1), seed=3)
        shutil.rmtree(cfg.result_dir, ignore_errors=True)
        sweep.verify_model(net, cfg, model_name="BaB-1", resume=False,
                           partition_span=(0, n_parts))  # warm (untimed)
        runs = []
        for _ in range(BENCH_REPEATS):
            shutil.rmtree(cfg.result_dir, ignore_errors=True)
            obs.registry().reset()  # launch counter lives here: delta = total
            t0 = time.perf_counter()
            rep = sweep.verify_model(net, cfg, model_name="BaB-1",
                                     resume=False,
                                     partition_span=(0, n_parts))
            dt = time.perf_counter() - t0
            decided = rep.counts["sat"] + rep.counts["unsat"]
            launches = profiling.launch_count()
            runs.append({
                "value": round(decided / dt, 2) if dt > 0 else 0.0,
                "elapsed_s": round(dt, 3),
                "decided_fraction": round(decided / n_parts, 4),
                "device_launches": launches,
                "launches_per_partition": round(launches / n_parts, 2)})
        rows[mode] = runs
    pps, lo_v, hi_v = _median_band(rows["device"])
    med = next(r for r in rows["device"] if r["value"] == pps)
    host_pps, _, _ = _median_band(rows["host"])
    host_med = next(r for r in rows["host"] if r["value"] == host_pps)
    print(json.dumps({
        "metric": f"device_bab_budgeted_decided_partitions_per_sec "
                  f"(synthetic german-BaB world, {n_parts} partitions, all "
                  f"engine-BaB-decided; median of {len(rows['device'])} "
                  f"repeats; bab_ab = host-frontier control, equal budget)",
        "value": pps,
        "unit": "partitions/sec",
        "min": lo_v,
        "max": hi_v,
        "runs": rows["device"],
        "decided_fraction": med["decided_fraction"],
        "device_launches": med["device_launches"],
        "launches_per_partition": med["launches_per_partition"],
        "bab_ab": {
            "pps_host": host_pps,
            "decided_fraction_host": host_med["decided_fraction"],
            "launches_host": host_med["device_launches"],
            "launches_per_partition_host": host_med[
                "launches_per_partition"],
            "launch_ratio_host_over_device": round(
                host_med["device_launches"]
                / max(med["device_launches"], 1), 2),
        },
    }), flush=True)


if __name__ == "__main__":
    import argparse

    _ap = argparse.ArgumentParser()
    _ap.add_argument("--trace-out", default=None)
    _ap.add_argument("--heartbeat-interval", type=float, default=0.0)
    _ap.add_argument("--xprof-dir", default=None)
    _a = _ap.parse_args()
    sys.exit(main(trace_out=_a.trace_out, heartbeat_s=_a.heartbeat_interval,
                  xprof_dir=_a.xprof_dir))

"""Headline benchmark: verified partitions/sec on the GC-1 German sweep.

Reference baseline (BASELINE.md, Appendix Table V, GC1/Age): 46 partitions
attempted in the 30-minute budget at a mean 43.19 s/partition on CPU —
0.02315 verified partitions/sec.  This benchmark runs the same query
(German Credit, PA=age, partition threshold 100 → 201 partitions) through
the TPU-native engine end-to-end (sound pruning, stage-0 certificates +
attack, branch-and-bound refinement) and reports decided partitions/sec.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""
from __future__ import annotations

import json
import sys
import time

REFERENCE_PARTITIONS_PER_SEC = 46 / (46 * 43.19)  # GC1/Age, Table V


def _probe_ok() -> bool:
    """Probe the default jax backend in a subprocess with a timeout.

    The tunnelled TPU platform hangs (rather than errors) when its relay is
    down; a hung benchmark is worse than a CPU number, so the probe gets 60s
    and main() re-execs under a forced-CPU environment on failure.
    """
    import os
    import subprocess

    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=60, capture_output=True, check=True,
        )
        return True
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        return False


def main() -> None:
    import os

    if not os.environ.get("FAIRIFY_TPU_BENCH_FALLBACK") and not _probe_ok():
        env = dict(os.environ, FAIRIFY_TPU_BENCH_FALLBACK="1", PYTHONPATH="",
                   JAX_PLATFORMS="cpu")
        import subprocess

        raise SystemExit(subprocess.run([sys.executable, __file__], env=env).returncode)

    import numpy as np

    from fairify_tpu.verify import engine, presets, sweep
    from __graft_entry__ import _flagship_net

    cfg = presets.get("GC").with_(
        result_dir="/tmp/fairify_tpu_bench",
        soft_timeout_s=10.0,
        hard_timeout_s=10 * 60.0,
        exact_certify_masks=False,  # parity pass off for the timing run
        engine=engine.EngineConfig(frontier_size=512, attack_samples=128,
                                   bab_attack_samples=16, soft_timeout_s=10.0),
    )
    net = _flagship_net()

    import shutil

    shutil.rmtree("/tmp/fairify_tpu_bench", ignore_errors=True)
    # Warm-up: compile the stage-0 kernels on a 2-partition slice.
    warm = cfg.with_(hard_timeout_s=1e-9, result_dir="/tmp/fairify_tpu_bench_warm")
    shutil.rmtree("/tmp/fairify_tpu_bench_warm", ignore_errors=True)
    try:
        sweep.verify_model(net, warm, model_name="warmup", resume=False)
    except Exception:
        pass

    t0 = time.perf_counter()
    report = sweep.verify_model(net, cfg, model_name="GC-1", resume=False)
    elapsed = time.perf_counter() - t0

    counts = report.counts
    decided = counts["sat"] + counts["unsat"]
    pps = decided / elapsed if elapsed > 0 else 0.0
    print(json.dumps({
        "metric": "verified_partitions_per_sec_per_chip (GC-1, PA=age, 201 partitions; "
                  f"sat={counts['sat']} unsat={counts['unsat']} unk={counts['unknown']})",
        "value": round(pps, 4),
        "unit": "partitions/sec",
        "vs_baseline": round(pps / REFERENCE_PARTITIONS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
